"""graftshard — collective-traffic & sharding auditor for the mesh
programs (GP4xx; ROADMAP item 3's static gate).

``graftprog`` ratchets each program's FLOPs/bytes/fingerprint, but those
are *per-device* views: a stray all-gather that replicates the full
param tree every step, a donated leaf silently resharded on entry, or a
params.sync publish that degrades from a device-to-device copy into a
host round-trip all pass the GP2xx/GP3xx gate with at most an opaque
bytes wobble. This module audits the **communication structure** of the
mesh-placed registry programs (``dp_superstep``, ``actor_step``/
``learner_step``, ``pop_dp_superstep``/``pop_learner_step``, the
synthetic dp×mp ``dpmp_block``) by compiling them under the fixed audit
meshes and parsing the partitioned HLO, plus the ``params.sync``
publish as a static sharding-pair transfer check (a cross-mesh
``device_put`` never lowers to HLO — the runtime executes it — so its
audit is the src/dst shard-map comparison, which is exactly the
property that decides copy-vs-gather).

**Comms rules** (ratcheted against the ``comms``/``transfers`` sections
of ``analysis/programs.json``):

========  ==============================================================
GP401     unbaselined collective: an all-reduce / all-gather /
          reduce-scatter / collective-permute / all-to-all op kind (or
          occurrence count past the baselined one) appearing in a mesh
          program — new collectives must be consciously accepted.
GP402     per-program collective bytes (element-counted from the
          partitioned HLO result shapes) grew past the entry's
          tolerance — the interconnect-traffic twin of GP302.
GP403     replication blowup: an all-gather materializing a tensor at
          least as large as the program's largest sharded input leaf
          (full unsharded size) — the accidental-full-gather class.
GP404     boundary reshard: a donated input leaf whose compiled
          sharding differs from the sharding the donor was stamped
          with — or that entered unstamped and was compiled with a
          sharded entry layout (XLA copies on entry, defeating
          donation) — or a transfer leaf whose destination shards do
          not exist verbatim
          on any source device (the publish degrades to
          gather/reshard instead of a pure d2d copy).
GP405     logical-axis-rule violation: a program output whose lowered
          sharding does not match the sharding its declared logical
          axes map to under ``parallel/mesh.py LOGICAL_AXIS_RULES`` —
          the T5X-pattern dry-run gate for the dp×mp partitioner.
========  ==============================================================

Shrinkage (fewer collectives, smaller bytes) is a stale note, never a
failure — rerun ``--comms --write-programs`` to tighten, exactly like
the GP3xx ratchet. Raw mode (``--no-baseline``) reports only the
structural rules (GP403/404/405); GP401/402 are baseline-relative,
like GP300-302.
"""

from __future__ import annotations

import dataclasses
import re
from typing import Dict, List, Optional, Tuple

from .registry import AuditProgram, SkipProgram, TransferAudit

#: rule id -> one-line summary (full catalog: docs/ANALYSIS.md)
GP4_RULES: Dict[str, str] = {
    "GP401": "unbaselined collective op kind/count in a mesh program",
    "GP402": "collective bytes grew past the baseline tolerance",
    "GP403": "replication blowup: all-gather materializes a full-size leaf",
    "GP404": "donated/published leaf resharded at a program boundary",
    "GP405": "lowered sharding violates a declared logical axis rule",
}

#: the op kinds the census counts (HLO instruction names, sync form;
#: async ``-start`` halves are folded in, ``-done`` halves skipped)
COLLECTIVE_KINDS = ("all-reduce", "all-gather", "reduce-scatter",
                    "collective-permute", "all-to-all")

#: default tolerance written for NEW comms/transfer baseline entries
#: (collective traffic is structural — tighter than the FLOP budgets)
COMMS_TOLERANCE = 0.10

_HLO_TYPE_RE = re.compile(r"([a-z][a-z0-9]{1,4})\[([0-9,]*)\]")
_COLLECTIVE_RE = re.compile(
    r"=\s+(?P<result>[^=]*?)\s+"
    r"(?P<op>all-reduce|all-gather|reduce-scatter|collective-permute|"
    r"all-to-all)(?P<suffix>-start|-done)?\(")

_DTYPE_BYTES = {
    "pred": 1, "s8": 1, "u8": 1, "f8e4m": 1, "f8e5m": 1,
    "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4,
    "s64": 8, "u64": 8, "f64": 8, "c64": 8, "c128": 16,
}


def _type_bytes(dtype: str, shape_csv: str) -> int:
    n = 1
    for d in shape_csv.split(","):
        if d.strip():
            n *= int(d)
    return n * _DTYPE_BYTES.get(dtype, 4)


# ------------------------------------------------------- replica groups

def _iota_groups(g: int, s: int, dims: List[int],
                 perm: Optional[List[int]]) -> List[List[int]]:
    """Decode HLO iota replica groups ``[g,s]<=[dims]T(perm)``: device
    order is iota over ``dims`` (optionally transposed), reshaped to
    ``g`` groups of ``s``."""
    import numpy as np
    order = np.arange(int(np.prod(dims))).reshape(dims)
    if perm is not None:
        order = order.transpose(perm)
    return order.reshape(g, s).tolist()


def _parse_groups(line: str) -> Optional[List[List[int]]]:
    m = re.search(r"replica_groups=\{(\{[^}]*\}(?:,\{[^}]*\})*)\}", line)
    if m:
        return [[int(d) for d in grp.split(",") if d.strip()]
                for grp in re.findall(r"\{([^}]*)\}", m.group(1))]
    m = re.search(
        r"replica_groups=\[(\d+),(\d+)\]<=\[([\d,]+)\]"
        r"(?:T\(([\d,]+)\))?", line)
    if m:
        g, s = int(m.group(1)), int(m.group(2))
        dims = [int(d) for d in m.group(3).split(",")]
        perm = ([int(d) for d in m.group(4).split(",")]
                if m.group(4) else None)
        return _iota_groups(g, s, dims, perm)
    return None


def _axis_groups(mesh_shape: Tuple[int, ...], axis: int) -> set:
    """The group set a collective running along exactly ``axis`` of a
    mesh of ``mesh_shape`` logical devices would carry."""
    import numpy as np
    idx = np.arange(int(np.prod(mesh_shape))).reshape(mesh_shape)
    moved = np.moveaxis(idx, axis, -1).reshape(-1, mesh_shape[axis])
    return set(frozenset(row) for row in moved.tolist())


def axis_label(groups: Optional[List[List[int]]],
               mesh_shape: Tuple[int, ...],
               axis_names: Tuple[str, ...]) -> str:
    """Attribute a replica-group set to a mesh axis name: the axis whose
    group pattern matches, ``+``-joined names when one group spans the
    whole mesh, ``mixed`` otherwise."""
    import numpy as np
    n = int(np.prod(mesh_shape))
    if not groups:
        return "?"
    gset = set(frozenset(g) for g in groups)
    if gset == {frozenset(range(n))}:
        return "+".join(axis_names) if len(axis_names) > 1 else \
            axis_names[0]
    for k, name in enumerate(axis_names):
        if mesh_shape[k] > 1 and gset == _axis_groups(mesh_shape, k):
            return name
    return "mixed"


def _permute_label(line: str, mesh_shape: Tuple[int, ...],
                   axis_names: Tuple[str, ...]) -> str:
    """collective-permute carries source_target_pairs, not groups: the
    axis is the one along which every pair's mesh coordinates differ."""
    import numpy as np
    m = re.search(r"source_target_pairs=\{([^}]*(?:\},\{[^}]*)*)\}", line)
    if not m:
        return "?"
    pairs = re.findall(r"\{(\d+),(\d+)\}", line)
    if not pairs:
        return "?"
    axes = set()
    for a, b in pairs:
        ca = np.unravel_index(int(a), mesh_shape)
        cb = np.unravel_index(int(b), mesh_shape)
        diff = [k for k in range(len(mesh_shape)) if ca[k] != cb[k]]
        axes.add(tuple(diff))
    if all(len(d) == 1 for d in axes):
        names = {axis_names[d[0]] for d in axes}
        if len(names) == 1:
            return names.pop()
    return "mixed"


# ---------------------------------------------------------------- census

def parse_collectives(hlo_text: str, mesh_shape: Tuple[int, ...],
                      axis_names: Tuple[str, ...]) -> Dict[str, dict]:
    """Partitioned-HLO text -> census ``{op kind: {"count", "bytes",
    "axes"}}``. Bytes are element-counted from each op's RESULT types
    (tuple results summed); axes are attributed from replica groups /
    source-target pairs against the program's logical mesh shape.
    ``-done`` halves of async pairs are skipped (their ``-start`` was
    counted), so a future async CPU lowering can't double-count."""
    census: Dict[str, dict] = {}
    biggest: Dict[str, int] = {}
    for line in hlo_text.splitlines():
        m = _COLLECTIVE_RE.search(line)
        if m is None or m.group("suffix") == "-done":
            continue
        op = m.group("op")
        nbytes = sum(_type_bytes(d, s)
                     for d, s in _HLO_TYPE_RE.findall(m.group("result")))
        if op == "collective-permute":
            label = _permute_label(line, mesh_shape, axis_names)
        else:
            label = axis_label(_parse_groups(line), mesh_shape,
                               axis_names)
        e = census.setdefault(op, {"count": 0, "bytes": 0, "axes": []})
        e["count"] += 1
        e["bytes"] += nbytes
        if label not in e["axes"]:
            e["axes"].append(label)
        biggest[op] = max(biggest.get(op, 0), nbytes)
    for e in census.values():
        e["axes"] = sorted(e["axes"])
    return census


def census_bytes(census: Dict[str, dict]) -> int:
    return sum(e["bytes"] for e in census.values())


def _gather_blowups(hlo_text: str, threshold: int) -> List[str]:
    """GP403 detail lines: all-gathers whose result is at least
    ``threshold`` bytes (the largest sharded input leaf's full
    unsharded size)."""
    out = []
    for line in hlo_text.splitlines():
        m = _COLLECTIVE_RE.search(line)
        if (m is None or m.group("op") != "all-gather"
                or m.group("suffix") == "-done"):
            continue
        types = _HLO_TYPE_RE.findall(m.group("result"))
        nbytes = sum(_type_bytes(d, s) for d, s in types)
        if nbytes >= threshold:
            shapes = ", ".join(f"{d}[{s}]" for d, s in types)
            out.append(
                f"all-gather materializes {shapes} ({nbytes} bytes) — at "
                f"least the program's largest sharded input leaf "
                f"({threshold} bytes) re-assembled whole on every "
                f"device (accidental full gather)")
    return out


# --------------------------------------------------------------- reports

@dataclasses.dataclass
class CommsReport:
    """Everything the comms audit measured about one mesh program."""

    name: str
    census: Dict[str, dict] = dataclasses.field(default_factory=dict)
    total_bytes: int = 0
    mesh: str = ""                      # e.g. "2x2 (data, model)"
    #: rule -> per-occurrence detail messages (GP403/404/405)
    rule_details: Dict[str, List[str]] = dataclasses.field(
        default_factory=dict)
    skipped: Optional[str] = None

    def rule_count(self, rule: str) -> int:
        return len(self.rule_details.get(rule, []))


@dataclasses.dataclass
class TransferReport:
    """The static src→dst sharding-pair audit of one registered
    transfer (the params.sync publish class)."""

    name: str
    leaves: int = 0
    bytes: int = 0
    #: "d2d-copy" | "local" | "reshard" (worst leaf wins)
    kind: str = "d2d-copy"
    rule_details: Dict[str, List[str]] = dataclasses.field(
        default_factory=dict)
    skipped: Optional[str] = None

    def rule_count(self, rule: str) -> int:
        return len(self.rule_details.get(rule, []))


# ----------------------------------------------------- program selection

def _named_sharding(leaf):
    from jax.sharding import NamedSharding
    sh = getattr(leaf, "sharding", None)
    return sh if isinstance(sh, NamedSharding) else None


def is_mesh_program(prog: AuditProgram) -> bool:
    """A program enters the comms audit iff any example-argument leaf is
    stamped with a NamedSharding — that's what makes it mesh-placed."""
    import jax
    if prog.skip is not None:
        return True          # skips surface as stale notes, never drop
    return any(_named_sharding(l) is not None
               for l in jax.tree_util.tree_leaves(prog.args))


def _program_mesh(prog: AuditProgram):
    """(shape tuple, axis names) of the first stamped NamedSharding —
    the logical mesh the census attributes collectives against."""
    import jax
    for leaf in jax.tree_util.tree_leaves(prog.args):
        sh = _named_sharding(leaf)
        if sh is not None:
            mesh = sh.mesh
            return tuple(mesh.shape[a] for a in mesh.axis_names), \
                tuple(mesh.axis_names)
    return (1,), ("?",)


# ----------------------------------------------------------------- audit

def _resharded_donations(prog: AuditProgram, compiled) -> List[str]:
    """GP404 (program form): donated arg leaves whose compiled input
    sharding is not equivalent to the stamped one — the runtime copies
    the buffer into the new layout on entry, and a copied buffer cannot
    be donated in place."""
    import jax
    from jax.sharding import Sharding
    in_sh = compiled.input_shardings[0]
    out: List[str] = []
    for i in prog.donate_argnums:
        if i >= len(prog.args):
            continue
        leaves = jax.tree_util.tree_leaves(prog.args[i])
        shs = jax.tree_util.tree_leaves(
            in_sh[i], is_leaf=lambda x: isinstance(x, Sharding))
        for leaf, got in zip(leaves, shs):
            want = _named_sharding(leaf)
            if want is None:
                # No declared placement: GSPMD is free to pick the entry
                # layout, and when it picks a sharded one the caller's
                # (undeclared) buffer is resharded on dispatch — the
                # donation frees the copy, not the original.
                if got is not None and not got.is_fully_replicated:
                    out.append(
                        f"donated leaf {getattr(leaf, 'dtype', '?')}"
                        f"{list(getattr(leaf, 'shape', ()))} has no "
                        f"stamped sharding but compiled with sharded "
                        f"entry layout {got} — the dispatch-time reshard "
                        f"copy defeats donation")
                continue
            ndim = len(getattr(leaf, "shape", ()))
            if not want.is_equivalent_to(got, ndim):
                out.append(
                    f"donated leaf {getattr(leaf, 'dtype', '?')}"
                    f"{list(getattr(leaf, 'shape', ()))} stamped "
                    f"{want.spec} but compiled as {got} — resharded on "
                    f"entry, the silent copy defeats donation")
    return out


def _logical_violations(prog: AuditProgram, compiled) -> List[str]:
    """GP405: declared expected output shardings
    (``AuditProgram.expected_output_shardings``, built from
    ``parallel/mesh.py LOGICAL_AXIS_RULES``) vs what lowering chose."""
    import jax
    from jax.sharding import Sharding
    expected = prog.expected_output_shardings
    if expected is None:
        return []
    got_tree = compiled.output_shardings
    exp_leaves = jax.tree_util.tree_leaves(
        expected, is_leaf=lambda x: isinstance(x, Sharding))
    got_leaves = jax.tree_util.tree_leaves(
        got_tree, is_leaf=lambda x: isinstance(x, Sharding))
    out: List[str] = []
    if len(exp_leaves) != len(got_leaves):
        return [f"declared {len(exp_leaves)} output sharding leaves but "
                f"the program lowered {len(got_leaves)} — the logical "
                f"spec no longer matches the program's output structure"]
    for i, (want, got) in enumerate(zip(exp_leaves, got_leaves)):
        if want is None:
            continue
        ndim = len(want.spec) if hasattr(want, "spec") else 0
        try:
            ok = want.is_equivalent_to(got, ndim)
        except Exception:  # noqa: BLE001 — differing sharding classes
            ok = False
        if not ok:
            out.append(
                f"output leaf {i} lowered as {got} but LOGICAL_AXIS_"
                f"RULES declare {want.spec} — the partitioner dry-run "
                f"gate (docs/ANALYSIS.md GP405)")
    return out


def lower_comms_program(name: str, prog: AuditProgram):
    """Phase 1 (serial): trace + lower one mesh program. Returns the
    (report, lowered, traced) triple; ``lowered`` is None when the
    program skipped."""
    report = CommsReport(name=name)
    if prog.skip is not None:
        report.skipped = prog.skip
        return report, None
    try:
        traced = prog.fn.trace(*prog.args, **prog.kwargs)
    except SkipProgram as e:
        report.skipped = str(e)
        return report, None
    return report, traced.lower()


def finish_comms_program(report: CommsReport, prog: AuditProgram,
                         compiled) -> CommsReport:
    """Phase 2: parse the partitioned HLO of the compiled program and
    run every comms rule."""
    import jax
    shape, names = _program_mesh(prog)
    report.mesh = "x".join(str(s) for s in shape) + f" ({', '.join(names)})"
    text = compiled.as_text()
    report.census = parse_collectives(text, shape, names)
    report.total_bytes = census_bytes(report.census)

    details: Dict[str, List[str]] = {}
    sharded_bytes = [
        _leaf_bytes(l) for l in jax.tree_util.tree_leaves(prog.args)
        if (sh := _named_sharding(l)) is not None
        and not sh.is_fully_replicated]
    if sharded_bytes:
        if (d := _gather_blowups(text, max(sharded_bytes))):
            details["GP403"] = d
    if prog.donate_argnums:
        if (d := _resharded_donations(prog, compiled)):
            details["GP404"] = d
    if (d := _logical_violations(prog, compiled)):
        details["GP405"] = d
    report.rule_details = details
    return report


def _leaf_bytes(leaf) -> int:
    import numpy as np
    shape = getattr(leaf, "shape", ())
    dtype = getattr(leaf, "dtype", None)
    n = 1
    for d in shape:
        n *= int(d)
    return n * (np.dtype(dtype).itemsize if dtype is not None else 4)


def audit_comms_registry(progs: Dict[str, AuditProgram],
                         workers: int = 2) -> List[CommsReport]:
    """Audit every mesh program: lower serially (tracing shares global
    jax state), compile concurrently (XLA releases the GIL — on the
    2-core gate box this roughly halves the dominant compile phase),
    then parse each partitioned module."""
    from concurrent.futures import ThreadPoolExecutor
    lowered: List[Tuple[CommsReport, AuditProgram, object]] = []
    for name, prog in progs.items():
        rep, lo = lower_comms_program(name, prog)
        lowered.append((rep, prog, lo))
    with ThreadPoolExecutor(max_workers=max(1, workers)) as pool:
        compiled = list(pool.map(
            lambda t: None if t[2] is None else t[2].compile(), lowered))
    out: List[CommsReport] = []
    for (rep, prog, _), co in zip(lowered, compiled):
        out.append(rep if co is None
                   else finish_comms_program(rep, prog, co))
    return out


# -------------------------------------------------------------- transfers

def _canon_index(idx, shape) -> tuple:
    """Canonical hashable form of a devices_indices_map value: a tuple
    of (start, stop) per dimension with slices resolved."""
    out = []
    for sl, dim in zip(idx, shape):
        start, stop, step = sl.indices(dim)
        out.append((start, stop, step))
    return tuple(out)


def audit_transfer(name: str, ta: TransferAudit) -> TransferReport:
    """Static transfer census: per leaf, compare the source sharding's
    device→index map against the destination's. A destination shard
    that exists verbatim on some source device is a pure device-to-
    device copy (or free, when the destination device already holds
    it); anything else forces a gather/reshard on the publish path —
    the GP404 host-round-trip class. Nothing is executed or lowered."""
    import jax
    report = TransferReport(name=name)
    if ta.skip is not None:
        report.skipped = ta.skip
        return report
    src_leaves = jax.tree_util.tree_leaves(ta.src)
    dst_leaves = jax.tree_util.tree_leaves(
        ta.dst_shardings,
        is_leaf=lambda x: hasattr(x, "devices_indices_map"))
    kinds = {"local": 0, "d2d-copy": 0, "reshard": 0}
    details: List[str] = []
    moved = 0
    for leaf, dst_sh in zip(src_leaves, dst_leaves):
        shape = tuple(leaf.shape)
        src_sh = _named_sharding(leaf)
        if src_sh is None:
            continue
        src_map = {}
        for dev, idx in src_sh.devices_indices_map(shape).items():
            src_map.setdefault(_canon_index(idx, shape), set()).add(dev)
        leaf_kind = "local"
        n_elems = 1
        for d in shape:
            n_elems *= int(d)
        itemsize = _leaf_bytes(leaf) // max(1, n_elems)
        for dev, idx in dst_sh.devices_indices_map(shape).items():
            c = _canon_index(idx, shape)
            holders = src_map.get(c)
            if holders is None:
                leaf_kind = "reshard"
                break
            if dev in holders:
                continue                       # already in place, free
            leaf_kind = max(leaf_kind, "d2d-copy",
                            key=["local", "d2d-copy", "reshard"].index)
            moved += _shard_bytes(c, itemsize)
        kinds[leaf_kind] += 1
        if leaf_kind == "reshard":
            details.append(
                f"leaf {leaf.dtype}{list(shape)}: destination shard "
                f"({dst_sh}) does not exist verbatim on any source "
                f"device ({src_sh}) — the publish lowers as a "
                f"gather/reshard (host round-trip risk), not a pure "
                f"d2d copy")
    report.leaves = sum(kinds.values())
    report.bytes = moved
    report.kind = ("reshard" if kinds["reshard"] else
                   "d2d-copy" if kinds["d2d-copy"] else "local")
    if details:
        report.rule_details = {"GP404": details}
    return report


def _shard_bytes(canon_idx: tuple, itemsize: int) -> int:
    n = 1
    for start, stop, step in canon_idx:
        n *= max(0, (stop - start + step - 1) // step)
    return max(n, 1) * itemsize


# ----------------------------------------------------------------- ratchet

def _ProgFinding(program: str, rule: str, message: str):
    from .graftprog import ProgFinding
    return ProgFinding(program, rule, message)


def compare_comms(reports: List[CommsReport],
                  transfers: List[TransferReport],
                  baseline: dict) -> Tuple[List[object], List[str]]:
    """-> (new_findings, stale_notes) against the ``comms`` sections of
    programs.json entries plus its top-level ``transfers`` table — the
    graftprog ratchet contract (regressions past tolerance fail,
    improvements and vanished entries warn)."""
    findings: List[object] = []
    stale: List[str] = []
    base_programs = baseline.get("programs", {})
    base_transfers = baseline.get("transfers", {})
    seen = set()
    for rep in reports:
        seen.add(rep.name)
        if rep.skipped is not None:
            stale.append(f"{rep.name}: skipped ({rep.skipped})")
            continue
        entry = base_programs.get(rep.name, {})
        comms = entry.get("comms")
        if comms is None:
            if rep.census or rep.rule_details:
                for kind, e in sorted(rep.census.items()):
                    findings.append(_ProgFinding(
                        rep.name, "GP401",
                        f"no comms baseline — {e['count']}x {kind} "
                        f"({e['bytes']} bytes, axes "
                        f"{'/'.join(e['axes'])}) unaccounted; accept "
                        f"with --comms --write-programs (plus a "
                        f"justification)"))
                for rule, msgs in sorted(rep.rule_details.items()):
                    findings.extend(_ProgFinding(rep.name, rule, m)
                                    for m in msgs)
            continue
        base_census = comms.get("collectives", {})
        for kind, e in sorted(rep.census.items()):
            allowed = int(base_census.get(kind, {}).get("count", 0))
            if e["count"] > allowed:
                findings.append(_ProgFinding(
                    rep.name, "GP401",
                    f"{e['count']}x {kind} > {allowed} baselined "
                    f"({e['bytes']} bytes, axes {'/'.join(e['axes'])}) "
                    f"— a new collective moved into this program; "
                    f"justify and --comms --write-programs, or fix"))
            elif e["count"] < allowed:
                stale.append(f"{rep.name}: {kind} count dropped "
                             f"{allowed} -> {e['count']} (rerun --comms "
                             f"--write-programs to tighten)")
        for kind in sorted(set(base_census) - set(rep.census)):
            stale.append(f"{rep.name}: baselined collective {kind} no "
                         f"longer present (rerun --comms "
                         f"--write-programs to tighten)")
        tol = float(comms.get("tolerance", COMMS_TOLERANCE))
        base_bytes = comms.get("bytes")
        if base_bytes is not None and base_bytes > 0:
            if rep.total_bytes > base_bytes * (1.0 + tol):
                findings.append(_ProgFinding(
                    rep.name, "GP402",
                    f"collective bytes {rep.total_bytes} > baselined "
                    f"{base_bytes} (+{(rep.total_bytes / base_bytes - 1) * 100:.1f}%,"
                    f" tolerance {tol * 100:.0f}%) — justify and "
                    f"--comms --write-programs, or fix the regression"))
            elif rep.total_bytes < base_bytes * (1.0 - tol):
                stale.append(f"{rep.name}: collective bytes improved "
                             f"{base_bytes} -> {rep.total_bytes} (rerun "
                             f"--comms --write-programs to tighten)")
        elif base_bytes in (None, 0) and rep.total_bytes:
            # kinds were baselined but bytes never — treat as growth
            # from zero past any tolerance
            findings.append(_ProgFinding(
                rep.name, "GP402",
                f"collective bytes {rep.total_bytes} with no byte "
                f"budget baselined — --comms --write-programs"))
        _rule_ratchet(findings, stale, rep, comms.get("rules", {}),
                      "--comms --write-programs")
    for name in sorted(n for n, e in base_programs.items()
                       if "comms" in e and n not in seen):
        stale.append(f"{name}: baselined comms entry no longer audited")

    tseen = set()
    for rep in transfers:
        tseen.add(rep.name)
        if rep.skipped is not None:
            stale.append(f"{rep.name}: skipped ({rep.skipped})")
            continue
        entry = base_transfers.get(rep.name)
        if entry is None:
            findings.append(_ProgFinding(
                rep.name, "GP401",
                f"transfer has no baseline entry ({rep.leaves} leaves, "
                f"{rep.bytes} bytes, kind {rep.kind}) — accept with "
                f"--comms --write-programs (plus a justification)"))
            for rule, msgs in sorted(rep.rule_details.items()):
                findings.extend(_ProgFinding(rep.name, rule, m)
                                for m in msgs)
            continue
        if rep.kind != entry.get("kind"):
            findings.append(_ProgFinding(
                rep.name, "GP401",
                f"transfer kind changed {entry.get('kind')!r} -> "
                f"{rep.kind!r} — the publish no longer moves the way "
                f"the baseline promises"))
        tol = float(entry.get("tolerance", COMMS_TOLERANCE))
        base_bytes = entry.get("bytes", 0)
        if base_bytes and rep.bytes > base_bytes * (1.0 + tol):
            findings.append(_ProgFinding(
                rep.name, "GP402",
                f"transfer bytes {rep.bytes} > baselined {base_bytes} "
                f"(+{(rep.bytes / base_bytes - 1) * 100:.1f}%, tolerance "
                f"{tol * 100:.0f}%)"))
        elif base_bytes and rep.bytes < base_bytes * (1.0 - tol):
            stale.append(f"{rep.name}: transfer bytes improved "
                         f"{base_bytes} -> {rep.bytes} (rerun --comms "
                         f"--write-programs to tighten)")
        _rule_ratchet(findings, stale, rep, entry.get("rules", {}),
                      "--comms --write-programs")
    for name in sorted(set(base_transfers) - tseen):
        stale.append(f"{name}: baselined transfer no longer registered")
    return findings, stale


def _rule_ratchet(findings, stale, rep, base_rules: dict,
                  accept_hint: str) -> None:
    for rule in ("GP403", "GP404", "GP405"):
        allowed = int(base_rules.get(rule, {}).get("count", 0))
        msgs = rep.rule_details.get(rule, [])
        if len(msgs) > allowed:
            for m in msgs[allowed:]:
                findings.append(_ProgFinding(rep.name, rule, m))
            findings.append(_ProgFinding(
                rep.name, rule,
                f"{len(msgs)} occurrence(s) > {allowed} baselined"))
        elif len(msgs) < allowed:
            stale.append(f"{rep.name}: {rule} count dropped {allowed} "
                         f"-> {len(msgs)} (fixed? rerun {accept_hint} "
                         f"to tighten)")


def raw_findings(reports: List[CommsReport],
                 transfers: List[TransferReport]) -> List[object]:
    """``--no-baseline`` mode: only the structural rules (GP403/404/405)
    are meaningful without a baseline — GP401/402 are ratchets, exactly
    like GP300-302 in the program audit."""
    out: List[object] = []
    for rep in list(reports) + list(transfers):
        if rep.skipped is not None:
            continue
        for rule, msgs in sorted(rep.rule_details.items()):
            out.extend(_ProgFinding(rep.name, rule, m) for m in msgs)
    return out
