"""graftlint — JAX tracing-hygiene static analysis over the package.

The superstep work (docs/SPEC.md §8) exposed a class of bug no unit test
catches until the program runs on a device: host syncs hiding in the hot
loop (a blocking ``device_get`` cost ~0.66 s/iter under the axon tunnel,
BASELINE.md), a shared zero-buffer tripping XLA's donate-twice check
(``NormState.create``), and silent retraces that erase the
dispatch-amortization win. Podracer/Anakin-style throughput (PAPERS.md)
is exactly the property "one compiled program, zero host round-trips" —
this module checks it with tooling instead of reviewer vigilance.

Rules (catalog with rationale + examples: docs/ANALYSIS.md):

========  ==============================================================
GL101     Python ``if``/``while``/ternary branching on a traced value
          inside a traced function (concretization error at trace time,
          or a silent per-value retrace if the value is marked static).
GL102     Host/numpy calls on traced values in traced code: ``float()``
          / ``int()`` / ``bool()`` / ``np.*(tracer)`` / ``.item()`` /
          ``.tolist()`` / ``jax.device_get`` — each one is a forced
          device→host sync (or a trace-time error).
GL103     ``random.*`` / ``np.random.*`` inside traced code: host RNG is
          invisible to tracing — the draw is baked in at trace time as a
          constant, silently reused by every later call.
GL104     ``jnp``/``lax`` ops inside a Python ``for`` loop in traced
          code: the loop unrolls into the XLA graph (compile time scales
          with trip count) — the unrolled-scan smell; use ``lax.scan``.
GL105     ``jax.device_get`` / ``block_until_ready`` in a hot-path
          module (driver loop, learner, replay, runners): every one is a
          potential pipeline stall; each accepted use carries a baseline
          justification.
GL106     ``time.*`` / ``datetime.*`` in traced code: trace-time
          nondeterminism baked into the compiled program as a constant.
GL107     One allocation passed to two or more fields of a single
          constructor call (the ``NormState.create`` shared-zeros bug:
          donating a state whose leaves alias one buffer trips XLA's
          "donate the same buffer twice" check at dispatch).
GL108     Module-level import never referenced (dead import).
GL109     Array built OUTSIDE a traced function (module level, or in a
          non-traced builder) and referenced inside one via closure:
          the tracer bakes it into the program as a constant (GP202's
          AST-side companion) — duplicated per executable, silently
          stale if the binding is later updated. Pass it as an
          argument instead.
GL110     A device-boundary wrapper call (``_watched`` / ``_sync_point``
          / ``_dispatch``) whose literal phase is not registered in
          ``obs/spans.KNOWN_PHASES``: the graftscope span/flight
          coverage (and the GL110 check itself) is keyed on that set,
          so an unregistered phase is a dispatch boundary whose hangs
          and failures leave no telemetry trail — register it.
GL111     Bare ``lock.acquire()`` without ``timeout=`` (or
          ``blocking=False``) in a liveness-critical module
          (``LOCK_PATH_GLOBS``: the driver, serve/, the watchdog,
          obs/): a stuck holder wedges the thread with no watchdog
          escape — the PR 4 save_lock class. ``with lock:`` is exempt
          (the idiom for short critical sections).
GL112     Raw ``flax.serialization.msgpack_restore`` /
          ``from_state_dict`` in a driver/serve module
          (``CKPT_PATH_GLOBS``): checkpoint bytes must enter through
          ``utils/checkpoint.py``'s verify path (checksum gate, format
          migration, shard assembly, elastic routing) — a raw
          deserialize dodges all four and resurrects the torn-read and
          stale-format classes the checkpoint layer exists to kill.
          The two standing serve-layer loads (an exported artifact
          blob with its own recorded sha256, and a template restore
          already downstream of ``restore_host_state``) are baselined
          with justifications, not exempted by rule.
========  ==============================================================

Scope and honesty about limits: "traced code" means functions that are
*visibly* traced in the same module — decorated with ``jax.jit`` (incl.
``partial(jax.jit, ...)``) / ``vmap`` / ``grad`` / ``checkpoint`` etc.,
or passed by name into a tracing entry point (``jax.jit(f)``,
``lax.scan(body, ...)``, ``lax.cond``, ``lax.while_loop``, ...), plus
defs nested inside those. There is no transitive call-graph analysis:
a helper only ever called *from* traced code is not scanned. Likewise
"traced value" is a forward dataflow approximation (parameters minus
statics, plus locals assigned from expressions that touch traced names
or ``jax.numpy``/``jax.lax``-namespace calls). False positives are
expected and cheap: suppress a line with ``# graftlint: disable=GL1xx``
or accept it into ``analysis/baseline.json`` with a justification
(``baseline.py``); findings are identified by (rule, path, code-line
text), not line numbers, so unrelated edits don't churn the baseline.
"""

from __future__ import annotations

import ast
import dataclasses
import fnmatch
import re
from pathlib import Path
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

#: rule id -> one-line summary (the full catalog lives in docs/ANALYSIS.md)
RULES: Dict[str, str] = {
    "GL101": "Python branch on a traced value inside traced code",
    "GL102": "host/numpy call on a traced value inside traced code",
    "GL103": "host RNG (random.* / np.random.*) inside traced code",
    "GL104": "jnp/lax ops inside a Python for loop (unrolled-scan smell)",
    "GL105": "device_get / block_until_ready in a hot-path module",
    "GL106": "time.* / datetime.* nondeterminism inside traced code",
    "GL107": "one allocation aliased across fields of one constructor",
    "GL108": "dead import (module-level import never referenced)",
    "GL109": "closure-captured array constant in traced code (bake hazard)",
    "GL110": "device-boundary wrapper phase missing from obs span registry",
    "GL111": "bare lock acquire() without timeout in a liveness-critical "
             "module",
    "GL112": "raw checkpoint deserialize outside utils/checkpoint's "
             "verify path",
}

#: driver helper names whose first argument is a span/watchdog phase
#: (run.py). GL110 checks literal phases at their call sites against
#: the span registry parsed from SPAN_REGISTRY_PATH.
SPAN_WRAPPERS = frozenset({"_watched", "_sync_point", "_dispatch"})
#: where the span-phase registry lives (parsed by AST, never imported —
#: the lint CLI stays jax-free and import-free)
SPAN_REGISTRY_PATH = "t2omca_tpu/obs/spans.py"

#: modules whose host syncs are throughput hazards (GL105). Matched with
#: fnmatch against the repo-relative posix path.
HOT_PATH_GLOBS: Tuple[str, ...] = (
    "t2omca_tpu/run.py",
    "t2omca_tpu/learners/*.py",
    "t2omca_tpu/components/episode_buffer.py",
    "t2omca_tpu/components/host_replay.py",
    "t2omca_tpu/runners/*.py",
    # the kernel layer IS the hot path: a device_get/block_until_ready
    # creeping into a kernel wrapper would stall every rollout scan step
    "t2omca_tpu/kernels/*.py",
)

#: modules where an unbounded ``lock.acquire()`` is a liveness hazard
#: (GL111): the driver loop, the serving fleet, the watchdog and the
#: telemetry plane all hold locks across device dispatches — a bare
#: acquire there is the PR 4 save_lock wedge class (a stuck holder
#: silently freezes the process with the watchdog unable to report).
#: Bounded forms — ``acquire(timeout=...)`` / ``acquire(blocking=False)``
#: / ``with lock:`` (the context manager is deliberately exempt: it is
#: the idiom for short critical sections that never span a dispatch) —
#: are fine. Matched with fnmatch like HOT_PATH_GLOBS.
LOCK_PATH_GLOBS: Tuple[str, ...] = (
    "t2omca_tpu/run.py",
    "t2omca_tpu/serve/*.py",
    "t2omca_tpu/utils/watchdog.py",
    "t2omca_tpu/obs/*.py",
)

#: modules where a RAW flax deserialize of checkpoint bytes is a
#: correctness hazard (GL112): the driver and the serving layer consume
#: checkpoints, and ``utils/checkpoint.py`` is the one sanctioned door —
#: its restore path owns the sha256 gate against torn/truncated writes,
#: the v3→v5 format migration chain, partial-save shard assembly and
#: the elastic topology routing (docs/RESILIENCE.md §6). A call that
#: goes straight to ``flax.serialization`` silently skips all of them.
#: utils/checkpoint.py itself is deliberately NOT listed.
CKPT_PATH_GLOBS: Tuple[str, ...] = (
    "t2omca_tpu/run.py",
    "t2omca_tpu/serve/*.py",
)

#: the flax deserializers GL112 polices (alias-resolved dotted names)
_RAW_CKPT_LOADS = frozenset({
    "flax.serialization.msgpack_restore",
    "flax.serialization.from_state_dict",
})

# tracing entry points: wrapping one of these around a function makes its
# body traced code. Canonical (alias-resolved) dotted names.
_TRACE_WRAPPERS = frozenset({
    "jax.jit", "jax.pmap", "jax.vmap", "jax.grad", "jax.value_and_grad",
    "jax.jacfwd", "jax.jacrev", "jax.hessian", "jax.checkpoint",
    "jax.remat", "jax.custom_jvp", "jax.custom_vjp", "jax.linearize",
})
# control-flow primitives that trace callables handed to them
_TRACE_CONSUMERS = frozenset({
    "jax.lax.scan", "jax.lax.cond", "jax.lax.while_loop",
    "jax.lax.fori_loop", "jax.lax.switch", "jax.lax.map",
    "jax.lax.associative_scan", "jax.lax.custom_root",
    "jax.lax.custom_linear_solve",
})
#: calls under these namespaces produce traced arrays (dataflow seed)
_ARRAY_PREFIXES = ("jax.numpy.", "jax.lax.", "jax.nn.", "jax.random.",
                   "jax.scipy.", "jax.ops.")
#: allocation calls whose result must not alias across donated leaves
_ALLOC_NAMES = frozenset(
    f"{ns}.{fn}" for ns in ("jax.numpy", "numpy")
    for fn in ("zeros", "ones", "full", "empty", "zeros_like", "ones_like",
               "full_like", "empty_like", "arange", "eye"))

#: jnp/np-namespace calls that return static metadata, not arrays —
#: capturing one by closure bakes nothing (GL109 exemption)
_NONARRAY_CALLS = frozenset({
    "dtype", "shape", "ndim", "size", "result_type", "promote_types",
    "issubdtype", "iinfo", "finfo", "can_cast", "isscalar"})

_SUPPRESS_RE = re.compile(
    r"#\s*graftlint:\s*disable(?:=(?P<rules>\S+))?")
_SKIP_FILE_RE = re.compile(r"#\s*graftlint:\s*skip-file")


@dataclasses.dataclass(frozen=True, order=True)
class Finding:
    """One lint hit. ``key()`` (rule, path, code) is the baseline
    identity — line numbers shift with every unrelated edit, the quoted
    code line doesn't."""

    path: str          # repo-relative posix path
    line: int
    col: int
    rule: str
    message: str
    code: str          # stripped source line at ``line``

    def key(self) -> Tuple[str, str, str]:
        return (self.rule, self.path, self.code)

    def format(self) -> str:
        return f"{self.path}:{self.line}:{self.col}: {self.rule} " \
               f"{self.message}"


def _dotted(node: ast.AST) -> Optional[str]:
    """``a.b.c`` attribute chain -> "a.b.c" (None for anything else)."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


class _ModuleLinter:
    """One parsed module: alias resolution, traced-region discovery, and
    the rule walks. Produces a deduplicated, line-sorted finding list."""

    def __init__(self, src: str, path: str, hot: Optional[bool] = None,
                 span_phases: Optional[Set[str]] = None):
        self.src = src
        self.path = path
        self.lines = src.splitlines()
        self.tree = ast.parse(src, filename=path)
        self.hot = (any(fnmatch.fnmatch(path, g) for g in HOT_PATH_GLOBS)
                    if hot is None else hot)
        #: registered span phases for GL110 (None = rule disabled: the
        #: registry file was absent or the caller didn't supply one)
        self.span_phases = span_phases
        #: local alias -> canonical module/function dotted path
        self.modmap: Dict[str, str] = {}
        #: function name -> [FunctionDef] (all scopes, by simple name)
        self.defs: Dict[str, List[ast.FunctionDef]] = {}
        #: id(FunctionDef) -> static parameter-name set
        self.statics: Dict[int, Set[str]] = {}
        self.findings: Set[Finding] = set()
        self._collect_imports()
        self._collect_defs()

    # ------------------------------------------------------------ aliases

    def _collect_imports(self) -> None:
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    if a.asname:
                        self.modmap[a.asname] = a.name
                    else:
                        root = a.name.split(".")[0]
                        self.modmap[root] = root
            elif isinstance(node, ast.ImportFrom):
                if node.module is None or node.level:
                    continue        # relative imports: package-internal
                for a in node.names:
                    if a.name == "*":
                        continue
                    self.modmap[a.asname or a.name] = \
                        f"{node.module}.{a.name}"

    def canonical(self, node: ast.AST) -> Optional[str]:
        """Alias-resolved dotted name of an expression (e.g. with
        ``import jax.numpy as jnp``, ``jnp.zeros`` -> "jax.numpy.zeros");
        None when the expression isn't a name chain."""
        dotted = _dotted(node)
        if dotted is None:
            return None
        root, _, rest = dotted.partition(".")
        base = self.modmap.get(root)
        if base is None:
            return dotted
        return f"{base}.{rest}" if rest else base

    # ------------------------------------------------------ traced region

    def _collect_defs(self) -> None:
        for node in ast.walk(self.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.defs.setdefault(node.name, []).append(node)

    def _static_params(self, fn: ast.FunctionDef,
                       call: Optional[ast.Call]) -> Set[str]:
        """static_argnames/static_argnums from a jit decorator or call
        site (literal values only — dynamic specs are invisible to AST)."""
        out: Set[str] = set()
        keywords = list(call.keywords) if call is not None else []
        args = [a.arg for a in fn.args.posonlyargs + fn.args.args]
        for kw in keywords:
            if kw.arg == "static_argnames":
                for n in ast.walk(kw.value):
                    if isinstance(n, ast.Constant) and isinstance(n.value,
                                                                  str):
                        out.add(n.value)
            elif kw.arg == "static_argnums":
                for n in ast.walk(kw.value):
                    if isinstance(n, ast.Constant) and isinstance(n.value,
                                                                  int):
                        if 0 <= n.value < len(args):
                            out.add(args[n.value])
        return out

    def traced_functions(self) -> List[Tuple[ast.FunctionDef, Set[str]]]:
        """(FunctionDef, static-param-names) for every function this
        module visibly hands to the tracer."""
        marked: Dict[int, Tuple[ast.FunctionDef, Set[str]]] = {}

        def mark(fn: ast.FunctionDef, statics: Set[str]) -> None:
            cur = marked.get(id(fn))
            marked[id(fn)] = (fn, (cur[1] | statics) if cur else statics)

        # decorator route: @jax.jit / @partial(jax.jit, static_argnames=..)
        for fns in self.defs.values():
            for fn in fns:
                for dec in fn.decorator_list:
                    call = dec if isinstance(dec, ast.Call) else None
                    target = call.func if call else dec
                    name = self.canonical(target)
                    if name == "functools.partial" and call and call.args:
                        name = self.canonical(call.args[0])
                    if name in _TRACE_WRAPPERS:
                        mark(fn, self._static_params(fn, call))
        # call-site route: jax.jit(f, ...), lax.scan(body, ...), ...
        for node in ast.walk(self.tree):
            if not isinstance(node, ast.Call):
                continue
            name = self.canonical(node.func)
            if name not in _TRACE_WRAPPERS | _TRACE_CONSUMERS:
                continue
            referenced: Set[str] = set()
            for arg in list(node.args) + [kw.value for kw in node.keywords]:
                for sub in ast.walk(arg):
                    if isinstance(sub, ast.Name):
                        referenced.add(sub.id)
            for ref in referenced:
                for fn in self.defs.get(ref, []):
                    mark(fn, self._static_params(fn, node)
                         if name in _TRACE_WRAPPERS else set())
        return list(marked.values())

    # ---------------------------------------------------------- emission

    def emit(self, node: ast.AST, rule: str, message: str) -> None:
        line, col = node.lineno, node.col_offset + 1
        code = (self.lines[line - 1].strip()
                if 0 < line <= len(self.lines) else "")
        m = _SUPPRESS_RE.search(self.lines[line - 1]) \
            if 0 < line <= len(self.lines) else None
        if m:
            named = m.group("rules")
            # bare `disable` suppresses everything on the line; a named
            # list suppresses exactly those rules (case-normalized so a
            # `disable=gl105` typo suppresses GL105, not the whole line)
            if named is None or rule in {r.strip().upper()
                                         for r in named.split(",")}:
                return
        self.findings.add(Finding(path=self.path, line=line, col=col,
                                  rule=rule, message=message, code=code))

    # ------------------------------------------------------ traced rules

    def _is_traced_expr(self, expr: ast.AST, traced: Set[str]) -> bool:
        for n in ast.walk(expr):
            if isinstance(n, ast.Name) and n.id in traced:
                return True
            if isinstance(n, ast.Call):
                c = self.canonical(n.func)
                if c and c.startswith(_ARRAY_PREFIXES):
                    return True
        return False

    def _traced_locals(self, fn: ast.FunctionDef, traced: Set[str]) -> Set[str]:
        """Forward dataflow to fixpoint: locals assigned from traced
        expressions become traced. Iterated until the set stops growing
        — the lattice only grows and is bounded by the local-name count,
        so this terminates; a fixed pass count would miss taint chains
        written in reverse definition order (w = z; z = y; y = x)."""
        traced = set(traced)
        while True:
            before = len(traced)
            for node in ast.walk(fn):
                if isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)) and node is not fn:
                    continue      # nested defs get their own analysis
                targets: List[ast.expr] = []
                value: Optional[ast.expr] = None
                if isinstance(node, ast.Assign):
                    targets, value = node.targets, node.value
                elif isinstance(node, (ast.AnnAssign, ast.AugAssign)):
                    targets, value = [node.target], node.value
                elif isinstance(node, ast.For):
                    targets, value = [node.target], node.iter
                if value is None or not self._is_traced_expr(value, traced):
                    continue
                for t in targets:
                    for n in ast.walk(t):
                        if isinstance(n, ast.Name):
                            traced.add(n.id)
            if len(traced) == before:
                break
        return traced

    @staticmethod
    def _static_test(test: ast.expr) -> bool:
        """Branch tests that are static even on tracers: identity
        against None, and isinstance/type checks."""
        if isinstance(test, ast.Compare) and all(
                isinstance(op, (ast.Is, ast.IsNot)) for op in test.ops):
            return True
        if isinstance(test, ast.Call) and isinstance(test.func, ast.Name) \
                and test.func.id in ("isinstance", "callable", "hasattr"):
            return True
        return False

    def _check_traced_function(self, fn: ast.FunctionDef,
                               inherited: Set[str],
                               statics: Set[str]) -> None:
        params = {a.arg for a in (fn.args.posonlyargs + fn.args.args
                                  + fn.args.kwonlyargs)}
        for extra in (fn.args.vararg, fn.args.kwarg):
            if extra is not None:
                params.add(extra.arg)
        traced = (params - statics - {"self", "cls"}) | inherited
        traced = self._traced_locals(fn, traced)

        def walk(node: ast.AST) -> None:
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef)):
                    # nested def: traced region too, closure names carry
                    self._check_traced_function(child, traced, set())
                    continue
                if isinstance(child, (ast.If, ast.While)) and \
                        not self._static_test(child.test):
                    if self._is_traced_expr(child.test, traced):
                        kind = ("while" if isinstance(child, ast.While)
                                else "if")
                        self.emit(child, "GL101",
                                  f"Python `{kind}` on a traced value in "
                                  f"traced code — use jnp.where/lax.cond "
                                  f"(or mark the argument static)")
                if isinstance(child, ast.IfExp) and \
                        not self._static_test(child.test) and \
                        self._is_traced_expr(child.test, traced):
                    self.emit(child, "GL101",
                              "ternary on a traced value in traced code "
                              "— use jnp.where")
                if isinstance(child, ast.For):
                    for sub in ast.walk(child):
                        if isinstance(sub, (ast.FunctionDef,
                                            ast.AsyncFunctionDef)):
                            break
                        if isinstance(sub, ast.Call):
                            c = self.canonical(sub.func)
                            if c and c.startswith(("jax.numpy.",
                                                   "jax.lax.", "jax.nn.")):
                                self.emit(
                                    child, "GL104",
                                    f"`{c}` inside a Python for loop in "
                                    f"traced code unrolls into the XLA "
                                    f"graph — use lax.scan/fori_loop")
                                break
                if isinstance(child, ast.Call):
                    self._check_traced_call(child, traced)
                walk(child)

        walk(fn)

    def _check_traced_call(self, call: ast.Call, traced: Set[str]) -> None:
        name = self.canonical(call.func)
        argvals = list(call.args) + [kw.value for kw in call.keywords]
        any_traced_arg = any(self._is_traced_expr(a, traced)
                             for a in argvals)
        if name in ("float", "int", "bool", "complex") and any_traced_arg:
            self.emit(call, "GL102",
                      f"`{name}()` on a traced value forces a host sync "
                      f"(concretization) in traced code")
        elif name in ("jax.device_get", "jax.block_until_ready"):
            self.emit(call, "GL102",
                      f"`{name}` inside traced code is a host round-trip "
                      f"baked into the traced program")
        elif name and name.startswith("numpy.random."):
            self.emit(call, "GL103",
                      f"`{name}` in traced code: host RNG draws become "
                      f"trace-time constants — use jax.random")
        elif name and (name == "random" or name.startswith("random.")):
            self.emit(call, "GL103",
                      f"`{name}` in traced code: host RNG draws become "
                      f"trace-time constants — use jax.random")
        elif name and name.startswith("numpy.") and any_traced_arg:
            self.emit(call, "GL102",
                      f"`{name}` on a traced value in traced code forces "
                      f"a host transfer — use jax.numpy")
        elif name and name.startswith(("time.", "datetime.")):
            self.emit(call, "GL106",
                      f"`{name}` in traced code is trace-time "
                      f"nondeterminism baked in as a constant")
        if isinstance(call.func, ast.Attribute) and \
                call.func.attr in ("item", "tolist") and not call.args and \
                self._is_traced_expr(call.func.value, traced):
            self.emit(call, "GL102",
                      f"`.{call.func.attr}()` on a traced value forces a "
                      f"host sync in traced code")

    # -------------------------------------------- closure-captured consts

    def _is_array_expr(self, expr: ast.AST) -> bool:
        """Expression that visibly builds an array: any call under the
        jax.numpy/jax.lax/numpy namespaces in it — excluding the
        helpers that return static metadata (dtypes, shapes, finfo),
        which are legal and common closure captures."""
        for n in ast.walk(expr):
            if isinstance(n, ast.Call):
                c = self.canonical(n.func)
                if c and (c.startswith(_ARRAY_PREFIXES)
                          or c.startswith("numpy.")) \
                        and c.rsplit(".", 1)[-1] not in _NONARRAY_CALLS:
                    return True
        return False

    def _collect_scopes(self) -> None:
        """Lexical scope tables for GL109 (computed once, on demand):
        per scope (FunctionDef id, or None for module) the set of bound
        names, the subset visibly bound to an array expression (with
        the binding node), and each function's enclosing-scope chain."""
        self._scope_bound: Dict[Optional[int], Set[str]] = {None: set()}
        self._scope_arrays: Dict[Optional[int], Dict[str, ast.AST]] = \
            {None: {}}
        self._scope_chain: Dict[int, Tuple[Optional[int], ...]] = {}
        class_ids: Set[int] = set()

        def bind(scope: Optional[int], name: str) -> None:
            self._scope_bound.setdefault(scope, set()).add(name)

        def walk(node: ast.AST, scope: Optional[int],
                 chain: Tuple[Optional[int], ...]) -> None:
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef)):
                    bind(scope, child.name)
                    fid = id(child)
                    # closure-visible chain: the current scope joins it
                    # only when it is a real closure scope — a class
                    # body is not one (methods cannot capture class
                    # attributes as free variables)
                    vis = chain if scope in class_ids \
                        else (scope,) + chain
                    self._scope_chain[fid] = vis
                    a = child.args
                    for p in (a.posonlyargs + a.args + a.kwonlyargs):
                        bind(fid, p.arg)
                    for extra in (a.vararg, a.kwarg):
                        if extra is not None:
                            bind(fid, extra.arg)
                    walk(child, fid, vis)
                    continue
                if isinstance(child, ast.ClassDef):
                    bind(scope, child.name)
                    # class-body bindings go to a sentinel scope that no
                    # chain ever includes: `class C: TABLE = jnp.…` is an
                    # attribute (C.TABLE), never a closure capture — it
                    # must neither flag GL109 nor shadow a genuine
                    # module-level binding of the same name
                    class_ids.add(id(child))
                    walk(child, id(child), chain)
                    continue
                if isinstance(child, (ast.Assign, ast.AnnAssign)):
                    targets = (child.targets
                               if isinstance(child, ast.Assign)
                               else [child.target])
                    arrayish = (child.value is not None
                                and self._is_array_expr(child.value))
                    for t in targets:
                        for n in ast.walk(t):
                            if isinstance(n, ast.Name):
                                bind(scope, n.id)
                                if arrayish:
                                    self._scope_arrays.setdefault(
                                        scope, {})[n.id] = child
                elif isinstance(child, ast.Name) and \
                        isinstance(child.ctx, (ast.Store, ast.Del)):
                    bind(scope, child.id)
                walk(child, scope, chain)

        walk(self.tree, None, ())

    def _check_closure_consts(self, fn: ast.FunctionDef,
                              traced_ids: Set[int]) -> None:
        """GL109: a name FREE in this traced function whose closure
        capture resolves — through the lexical scope chain — to an
        array built at module scope or in a non-traced builder: it is
        concrete at trace time and gets baked into the compiled program
        as a constant (the weights-captured-by-closure class; GP202
        audits the same hazard on the compiled side). A capture whose
        nearest binder is a function parameter or a traced region is a
        tracer, not a bakeable constant — never flagged. One finding
        per name, at the first reference."""
        local: Set[str] = set(self._scope_bound.get(id(fn), set()))
        for node in ast.walk(fn):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)) and node is not fn:
                local.add(node.name)
                if not isinstance(node, ast.ClassDef):
                    # nested-def params shadow outer bindings for every
                    # reference in that def's body — a module-level array
                    # name reused as a scan-body parameter is a tracer
                    # there, not a capture (coarse union: suppressing is
                    # the conservative direction)
                    a = node.args
                    for p in (a.posonlyargs + a.args + a.kwonlyargs):
                        local.add(p.arg)
                    for extra in (a.vararg, a.kwarg):
                        if extra is not None:
                            local.add(extra.arg)
            elif isinstance(node, ast.Name) and \
                    isinstance(node.ctx, (ast.Store, ast.Del)):
                local.add(node.id)
        flagged: Set[str] = set()
        # nested defs are walked here too (they are traced by
        # containment); independently-marked ones get their own pass,
        # and the findings set dedupes the overlap
        for node in ast.walk(fn):
            if not (isinstance(node, ast.Name)
                    and isinstance(node.ctx, ast.Load)):
                continue
            name = node.id
            if name in local or name in flagged:
                continue
            for scope in self._scope_chain.get(id(fn), (None,)):
                if name not in self._scope_bound.get(scope, set()):
                    continue
                src = self._scope_arrays.get(scope, {}).get(name)
                if src is not None and scope not in traced_ids:
                    flagged.add(name)
                    self.emit(node, "GL109",
                              f"`{name}` is an array built outside this "
                              f"traced function (line {src.lineno}) and "
                              f"captured by closure — trace bakes it in "
                              f"as a program constant; pass it as an "
                              f"argument")
                break                    # nearest binder wins either way

    # ------------------------------------------------- module-scope rules

    def _check_hot_path(self) -> None:
        if not self.hot:
            return
        for node in ast.walk(self.tree):
            if not isinstance(node, ast.Call):
                continue
            name = self.canonical(node.func)
            is_bur = (name == "jax.block_until_ready"
                      or (isinstance(node.func, ast.Attribute)
                          and node.func.attr == "block_until_ready"))
            if name == "jax.device_get" or is_bur:
                what = "jax.device_get" if name == "jax.device_get" \
                    else "block_until_ready"
                self.emit(node, "GL105",
                          f"`{what}` in a hot-path module stalls the "
                          f"dispatch pipeline — move to a cadence "
                          f"boundary or baseline with justification")

    def _check_bare_acquire(self) -> None:
        """GL111: explicit ``<something>.acquire()`` with neither a
        ``timeout=`` nor ``blocking=False`` in a liveness-critical
        module (``LOCK_PATH_GLOBS``). A positional first argument is
        the ``blocking`` flag — ``acquire(False)`` is bounded, any
        other positional form is treated as unbounded. Name-based:
        any ``.acquire`` attribute call counts (Lock, RLock,
        Condition, Semaphore all share the wedge semantics)."""
        if not any(fnmatch.fnmatch(self.path, g)
                   for g in LOCK_PATH_GLOBS):
            return
        for node in ast.walk(self.tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "acquire"):
                continue
            if any(kw.arg == "timeout" for kw in node.keywords):
                continue
            if any(kw.arg == "blocking"
                   and isinstance(kw.value, ast.Constant)
                   and kw.value.value is False for kw in node.keywords):
                continue
            if (node.args and isinstance(node.args[0], ast.Constant)
                    and node.args[0].value is False):
                continue
            self.emit(node, "GL111",
                      "bare `.acquire()` without a timeout in a "
                      "liveness-critical module: a stuck holder wedges "
                      "this thread with no watchdog escape (the PR 4 "
                      "save_lock class) — pass `timeout=` and handle "
                      "the False return, use `blocking=False`, or "
                      "baseline with a justification")

    def _check_raw_ckpt_loads(self) -> None:
        """GL112: a raw ``flax.serialization.msgpack_restore`` /
        ``from_state_dict`` call in a checkpoint-consuming module
        (``CKPT_PATH_GLOBS``). Name-based on the alias-resolved dotted
        path, with an attribute fallback for handles the alias map
        cannot see (``flax.serialization as ser``-style chains resolve;
        a bound method stored in a variable does not, and none exist in
        the repo today). Justified standing loads live in the baseline,
        not in a rule exemption — a NEW raw load must argue its case."""
        if not any(fnmatch.fnmatch(self.path, g)
                   for g in CKPT_PATH_GLOBS):
            return
        tails = {name.rsplit(".", 1)[1] for name in _RAW_CKPT_LOADS}
        for node in ast.walk(self.tree):
            if not isinstance(node, ast.Call):
                continue
            name = self.canonical(node.func)
            hit = name in _RAW_CKPT_LOADS or (
                name is None and isinstance(node.func, ast.Attribute)
                and node.func.attr in tails)
            if hit:
                what = name or node.func.attr
                self.emit(node, "GL112",
                          f"raw `{what}` deserializes checkpoint bytes "
                          f"outside utils/checkpoint.py's verify path — "
                          f"no checksum gate, no format migration, no "
                          f"shard assembly, no elastic routing; load "
                          f"through utils/checkpoint (or baseline with "
                          f"a justification for why this surface is "
                          f"already downstream of it)")

    def _check_donation_alias(self) -> None:
        for fns in self.defs.values():
            for fn in fns:
                allocs: Set[str] = set()
                for node in ast.walk(fn):
                    if isinstance(node, ast.Assign) and \
                            isinstance(node.value, ast.Call) and \
                            self.canonical(node.value.func) in _ALLOC_NAMES:
                        for t in node.targets:
                            if isinstance(t, ast.Name):
                                allocs.add(t.id)
                if not allocs:
                    continue
                for node in ast.walk(fn):
                    if not isinstance(node, ast.Call):
                        continue
                    name = self.canonical(node.func)
                    if name and (name.startswith(_ARRAY_PREFIXES)
                                 or name.startswith("numpy.")):
                        continue       # reads may alias; only state
                    counts: Dict[str, int] = {}
                    for a in list(node.args) + [kw.value
                                                for kw in node.keywords]:
                        if isinstance(a, ast.Name) and a.id in allocs:
                            counts[a.id] = counts.get(a.id, 0) + 1
                    for nm, c in counts.items():
                        if c >= 2:
                            self.emit(
                                node, "GL107",
                                f"allocation `{nm}` passed {c}x into one "
                                f"constructor: donated leaves must be "
                                f"distinct buffers (XLA donate-twice "
                                f"check) — allocate per field")

    def _check_span_phases(self) -> None:
        """GL110: every literal phase handed to a device-boundary
        wrapper (``_watched``/``_sync_point``/``_dispatch``) must be in
        the span registry — the graftscope coverage contract. Only
        plain-name calls with a literal first ``phase`` argument are
        checkable; dynamic phases are invisible to AST and skipped
        (none exist in the driver today, and introducing one dodges
        this coverage check — don't)."""
        if self.span_phases is None:
            return
        for node in ast.walk(self.tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Name)
                    and node.func.id in SPAN_WRAPPERS):
                continue
            phase = None
            if node.args and isinstance(node.args[0], ast.Constant) \
                    and isinstance(node.args[0].value, str):
                phase = node.args[0].value
            else:
                for kw in node.keywords:
                    if kw.arg == "phase" \
                            and isinstance(kw.value, ast.Constant) \
                            and isinstance(kw.value.value, str):
                        phase = kw.value.value
            if phase is not None and phase not in self.span_phases:
                self.emit(node, "GL110",
                          f"phase {phase!r} passed to "
                          f"`{node.func.id}` is not registered in "
                          f"obs/spans.KNOWN_PHASES — this dispatch "
                          f"boundary has no span/flight coverage "
                          f"contract; add it to the registry")

    def _check_dead_imports(self) -> None:
        if self.path.endswith("__init__.py"):
            return                     # re-export surface: imports ARE use
        imported: Dict[str, ast.AST] = {}
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    imported[a.asname or a.name.split(".")[0]] = node
            elif isinstance(node, ast.ImportFrom):
                if node.module == "__future__":
                    continue
                for a in node.names:
                    if a.name != "*":
                        imported[a.asname or a.name] = node
        used: Set[str] = set()
        for node in ast.walk(self.tree):
            if isinstance(node, (ast.Import, ast.ImportFrom)):
                continue
            if isinstance(node, ast.Name):
                used.add(node.id)
        for node in ast.walk(self.tree):        # __all__ re-exports count
            if isinstance(node, ast.Assign) and any(
                    isinstance(t, ast.Name) and t.id == "__all__"
                    for t in node.targets):
                for sub in ast.walk(node.value):
                    if isinstance(sub, ast.Constant) and \
                            isinstance(sub.value, str):
                        used.add(sub.value)
        for name, node in sorted(imported.items()):
            if name not in used:
                self.emit(node, "GL108",
                          f"`{name}` is imported but never used")

    # ------------------------------------------------------------- drive

    def run(self) -> List[Finding]:
        if any(_SKIP_FILE_RE.search(l) for l in self.lines[:10]):
            return []
        marked = self.traced_functions()
        traced_ids: Set[int] = set()
        for fn, _ in marked:
            for sub in ast.walk(fn):
                if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    traced_ids.add(id(sub))
        self._collect_scopes()
        for fn, statics in marked:
            self._check_traced_function(fn, set(), statics)
            self._check_closure_consts(fn, traced_ids)
        self._check_hot_path()
        self._check_bare_acquire()
        self._check_raw_ckpt_loads()
        self._check_donation_alias()
        self._check_dead_imports()
        self._check_span_phases()
        return sorted(self.findings,
                      key=lambda f: (f.path, f.line, f.col, f.rule))


# ---------------------------------------------------------------- frontend

def collect_span_phases(root: Path) -> Optional[Set[str]]:
    """Parse ``KNOWN_PHASES`` out of the span registry
    (``obs/spans.py``) by AST — never imported, so the lint CLI stays
    import-free. None (GL110 disabled) when the file or the assignment
    is absent; a registry that exists but parses to zero phases is
    still a live (maximally strict) rule."""
    path = Path(root) / SPAN_REGISTRY_PATH
    if not path.is_file():
        return None
    try:
        tree = ast.parse(path.read_text(), filename=str(path))
    except (OSError, SyntaxError):
        return None
    for node in ast.walk(tree):
        if not isinstance(node, ast.Assign):
            continue
        if not any(isinstance(t, ast.Name) and t.id == "KNOWN_PHASES"
                   for t in node.targets):
            continue
        return {n.value for n in ast.walk(node.value)
                if isinstance(n, ast.Constant)
                and isinstance(n.value, str)}
    return None


def lint_source(src: str, path: str = "<memory>",
                hot: Optional[bool] = None,
                span_phases: Optional[Set[str]] = None) -> List[Finding]:
    """Lint one source string (fixture entry point for the tests).
    ``span_phases`` arms GL110 (``lint_package`` supplies the real
    registry; fixtures pass their own set)."""
    return _ModuleLinter(src, path, hot=hot,
                         span_phases=span_phases).run()


def lint_file(path: Path, root: Path,
              span_phases: Optional[Set[str]] = None) -> List[Finding]:
    rel = path.resolve().relative_to(root.resolve()).as_posix()
    return lint_source(path.read_text(), rel, span_phases=span_phases)


def lint_package(root: Path,
                 paths: Optional[Sequence[Path]] = None) -> List[Finding]:
    """Lint every ``*.py`` under ``paths`` (default: ``root/t2omca_tpu``),
    reporting paths relative to ``root`` (the repo root)."""
    root = Path(root)
    if paths is None:
        paths = [root / "t2omca_tpu"]
    span_phases = collect_span_phases(root)
    findings: List[Finding] = []
    for p in paths:
        p = Path(p)
        files: Iterable[Path] = (sorted(p.rglob("*.py")) if p.is_dir()
                                 else [p])
        for f in files:
            findings.extend(lint_file(f, root, span_phases=span_phases))
    return findings
