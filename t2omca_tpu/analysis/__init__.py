"""Static analysis + runtime enforcement for JAX tracing hygiene.

Two halves (docs/ANALYSIS.md):

* ``graftlint`` — AST lint over the package for JAX-specific hazards
  (tracer branching, host calls in traced code, unrolled-scan smells,
  hot-path host syncs, donation aliasing, dead imports), ratcheted by
  the checked-in ``baseline.json``. CLI: ``python -m t2omca_tpu.analysis``
  (``scripts/lint.sh``; runs at the top of the tier-1 gate).
  ``graftrace`` (GT1xx, ``--threads``) is its concurrency sibling:
  thread-topology discovery + lock-discipline audit over the host
  threads, sharing the same baseline file and exit-code contract.
* ``guards`` — runtime context managers tests assert under:
  ``compile_budget(n)`` pins a program to n XLA compiles,
  ``no_transfer()`` turns implicit host transfers into errors.

``guards`` imports jax; the lint CLI must stay import-light (it runs in
front of every test batch), so guard names resolve lazily via module
``__getattr__`` instead of an eager import.
"""

from __future__ import annotations

from .baseline import (DEFAULT_BASELINE, DEFAULT_PROGRAMS, diff_baseline,
                       filter_family, load_baseline, load_programs,
                       save_baseline, save_programs)
from .graftlint import (HOT_PATH_GLOBS, RULES, Finding, lint_file,
                        lint_package, lint_source)
from .graftrace import (GT_RULES, trace_file, trace_package,
                        trace_source)

_GUARD_NAMES = ("compile_budget", "no_transfer", "CompileBudgetExceeded",
                "CompileEvents")
#: graftprog/registry surface — resolved lazily like the guards: the
#: modules are import-light themselves, but anything that *uses* them
#: pulls in jax, and the lint CLI must stay jax-free
_PROG_NAMES = {
    "GP_RULES": "graftprog", "ProgFinding": "graftprog",
    "ProgramReport": "graftprog", "audit_program": "graftprog",
    "audit_registry": "graftprog", "compare_reports": "graftprog",
    "fingerprint_text": "graftprog", "CONST_BYTES_DEFAULT": "graftprog",
    "AuditProgram": "registry", "AuditContext": "registry",
    "SkipProgram": "registry", "audit_config": "registry",
    "audit_context": "registry",
    "collect_default_programs": "registry",
}

__all__ = [
    "DEFAULT_BASELINE", "DEFAULT_PROGRAMS", "diff_baseline",
    "filter_family", "load_baseline", "load_programs", "save_baseline",
    "save_programs",
    "HOT_PATH_GLOBS", "RULES", "Finding", "lint_file", "lint_package",
    "lint_source",
    "GT_RULES", "trace_file", "trace_package", "trace_source",
    *_GUARD_NAMES, *sorted(_PROG_NAMES),
]


def __getattr__(name: str):
    if name in _GUARD_NAMES:
        from . import guards
        return getattr(guards, name)
    if name in _PROG_NAMES:
        import importlib
        mod = importlib.import_module(f".{_PROG_NAMES[name]}", __name__)
        return getattr(mod, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
