"""Program registry: one place where the hot compiled programs get names.

The driver builds its XLA programs inline (``run.run_sequential`` calls
``Experiment.jitted_programs`` / ``superstep_program`` and throws the
handles into the loop), so before this module nothing in the repo could
*enumerate* them — the auditor (``graftprog``), the budget baseline
(``analysis/programs.json``) and the compile-count tests each need a
stable name → buildable-program mapping. The registry provides it:
``run.py``, ``parallel/mesh.py`` and ``learners/qmix_learner.py`` each
expose a ``register_audit_programs(reg)`` hook that names its programs
once, and ``collect_default_programs()`` gathers them on demand.

Programs are built against ``audit_config()`` — a frozen tiny CPU
config (bf16 compute so the dtype-churn rule GP203 has teeth) — and are
**lowered from abstract avals only** (``jax.eval_shape`` state +
``ShapeDtypeStruct`` keys): the audit never runs an env step or a train
step, so it fits the tier-1 gate without a TPU and without paying real
rollout compute. Only entries marked ``compile=True`` pay an XLA
compile (for ``memory_analysis`` and optimized-HLO costs); the rest are
audited at the lowered (stable-HLO) level.

The example arguments deliberately mimic the DRIVER's avals — e.g.
``t_env`` is the weak-typed ``jnp.asarray(int)`` scalar the loop
passes — so the recorded fingerprint is the fingerprint of the program
the driver actually dispatches, and an aval drift between driver and
registry (say a weak-type fix on one side only) shows up as GP304.
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Dict, Optional, Tuple


class SkipProgram(RuntimeError):
    """Raised by a builder whose program cannot be built in this
    environment (e.g. the data-parallel program on a 1-device host);
    the auditor reports the skip and moves on — a skip is never a
    finding, matching the lint ratchet's stale-entry semantics.
    Hooks that detect the condition up front can instead register
    ``AuditProgram.skipped(reason)``."""


@dataclasses.dataclass(frozen=True)
class AuditProgram:
    """One buildable named program.

    ``fn`` is the *jitted* callable (so ``fn.trace``/``fn.lower`` serve
    the auditor); ``args``/``kwargs`` are example arguments — abstract
    ``ShapeDtypeStruct``/``eval_shape`` trees wherever possible.
    ``donate_argnums`` mirrors what the driver donates (the auditor
    checks every donated leaf is actually aliased — GP201).
    ``compile=True`` opts into the XLA compile for ``memory_analysis``
    + optimized-HLO costs (expensive: reserve it for the donated hot
    programs)."""

    fn: object
    args: Tuple = ()
    kwargs: Dict = dataclasses.field(default_factory=dict)
    donate_argnums: Tuple[int, ...] = ()
    compile: bool = False
    description: str = ""
    #: set when the program cannot be built in this environment; the
    #: auditor records the reason instead of tracing
    skip: Optional[str] = None
    #: declared output shardings (a pytree of ``NamedSharding``/None
    #: matching the program's outputs) derived from
    #: ``parallel.mesh.LOGICAL_AXIS_RULES`` — when set, the comms audit
    #: (``--comms``) checks the compiled ``output_shardings`` against it
    #: (GP405, the partitioner dry-run gate). ``None`` = not declared.
    expected_output_shardings: object = None

    @classmethod
    def skipped(cls, reason: str) -> "AuditProgram":
        return cls(fn=None, skip=reason)


@dataclasses.dataclass(frozen=True)
class TransferAudit:
    """One named cross-mesh transfer (the ``params.sync`` publish
    class). A cross-mesh ``jax.device_put`` never lowers to HLO — the
    runtime executes it directly — so its audit is the static
    src-sharding → dst-sharding comparison (``graftshard.
    audit_transfer``): ``src`` is a pytree of ShapeDtypeStructs stamped
    with the SOURCE shardings (the learner-mesh layout the donor
    produces), ``dst_shardings`` the matching pytree of destination
    ``Sharding``\\ s (what the publish requests). The audit classifies
    every leaf as local / pure d2d copy / reshard — reshard is the
    GP404 host-round-trip class."""

    src: object = None
    dst_shardings: object = None
    description: str = ""
    skip: Optional[str] = None

    @classmethod
    def skipped(cls, reason: str) -> "TransferAudit":
        return cls(skip=reason)


@dataclasses.dataclass
class AuditContext:
    """Shared build products every hook draws from: the tiny-config
    ``Experiment`` plus the ``eval_shape`` of its initial TrainState
    (abstract — building it allocates nothing)."""

    cfg: object
    exp: object
    ts_shape: object
    superstep_k: int

    @property
    def compute_dtype(self) -> str:
        return self.cfg.model.dtype


#: the registry: insertion-ordered name -> AuditProgram
Registry = Dict[str, AuditProgram]

_ctx_lock = threading.Lock()
_ctx: Optional[AuditContext] = None

#: the superstep depth every audit builds with — small (cheap compile)
#: but > 1 so the scan/gate structure is the real fused program's
AUDIT_SUPERSTEP_K = 2

#: registry program name -> substrings identifying its events in a
#: ``jax.profiler`` trace (graftscope device-time attribution,
#: ``obs/device_time.py``). The jitted wrapper functions in
#: ``run.Experiment.jitted_programs``/``superstep_program`` are named
#: ``_rollout``/``_insert``/``_train_iter``/``_superstep``; the device
#: tracks name the XLA module ``jit_<fn>`` while the host executor
#: track (the only one a CPU trace has — verified against a real
#: JAX 0.4.37 capture) names the call ``PjitFunction(<fn>)``. Both
#: forms are listed; the parser attributes one track per program, so
#: listing both never double-counts. Stable as long as the wrapper
#: names are (renaming one breaks attribution AND the checked-in GP304
#: fingerprint, so the programs.json re-baseline is the reminder).
#: Only the four driver hot programs are attributed:
#: ``dp_superstep``/``learner_train`` lower the same wrappers (or
#: ambiguous names) and would double-count.
TRACE_SYMBOLS = {
    "rollout": ("jit__rollout", "PjitFunction(_rollout)"),
    "insert": ("jit__insert", "PjitFunction(_insert)"),
    "train_iter": ("jit__train_iter", "PjitFunction(_train_iter)"),
    "superstep": ("jit__superstep", "PjitFunction(_superstep)"),
    # serving process only (serve/frontend.py) — never present in a
    # training trace, so attribution cannot double-count
    "serve_step": ("jit__serve_step", "PjitFunction(_serve_step)"),
    # attention kernel modes (kernels/attention.py). The jit symbols
    # appear only in standalone kernel dispatches (bench --kernels A/B,
    # the audit programs); inside a rollout/superstep trace the pallas
    # kernel instead shows up as its Mosaic kernel launch, whose name
    # carries the kernel function — listed so fused-kernel device time
    # is attributed instead of silently falling into the unattributed
    # bucket. The einsum mode has no distinct device symbol when fused
    # (XLA melts it into the surrounding fusion), so attn_xla only
    # attributes standalone dispatches.
    "attn_xla": ("jit__attn_xla", "PjitFunction(_attn_xla)"),
    "attn_pallas": ("jit__attn_pallas", "PjitFunction(_attn_pallas)",
                    "flash_attention_kernel"),
    # the flash BACKWARD kernels (PR 13). Inside a train trace the two
    # backward pallas programs show up as their Mosaic kernel-launch
    # names — listed so learner-side backward device time is attributed
    # instead of dropping into the unattributed bucket. (The substring
    # "flash_attention_kernel" does NOT match these names, so forward
    # and backward attribution can't cross-count.)
    "attn_pallas_bwd": ("jit__attn_pallas_bwd",
                        "PjitFunction(_attn_pallas_bwd)",
                        "flash_attention_bwd_dq_kernel",
                        "flash_attention_bwd_dkv_kernel"),
    # graftworld parameterized env programs (envs/graftworld.py). Like
    # the attention kernels these jit symbols appear only in standalone
    # dispatches (the audit, micro-benches) — inside a rollout the env
    # fuses into the scan body with no distinct symbol.
    "env_reset": ("jit__env_reset", "PjitFunction(_env_reset)"),
    "env_step": ("jit__env_step", "PjitFunction(_env_step)"),
    # graftpop population superstep (run.population_superstep_program):
    # the vmapped fused program dispatched by the population driver
    # loop — distinct wrapper name, so attribution never collides with
    # the single-member superstep
    "superstep_pop": ("jit__superstep_pop",
                      "PjitFunction(_superstep_pop)"),
    # graftshard dp×mp dry-run block (parallel/mesh.py dpmp_block): a
    # standalone audit-only dispatch — never fused into a driver trace,
    # so attribution cannot double-count
    "dpmp_block": ("jit__dpmp_block", "PjitFunction(_dpmp_block)"),
}


def audit_config():
    """The frozen tiny CPU config all default programs are built on.

    bf16 compute + f32 replay storage: the mixed-precision path is the
    one where a stray ``convert_element_type`` (GP203) or a baked f32
    constant (GP202) silently doubles bytes, so that is the path the
    canary watches. Shapes are test-scale — program *structure* (scan
    bodies, donation aliasing, dtype churn, callbacks) is shape-
    independent, and that structure is what the jaxpr rules audit;
    the cost ratchets are relative to this config's own baseline."""
    from ..config import (EnvConfig, ModelConfig, ReplayConfig, TrainConfig,
                          sanity_check)
    return sanity_check(TrainConfig(
        batch_size_run=2, batch_size=4, superstep=AUDIT_SUPERSTEP_K,
        env_args=EnvConfig(agv_num=3, mec_num=2, num_channels=2,
                           episode_limit=6, fast_norm=False),
        model=ModelConfig(emb=8, heads=2, depth=1, mixer_emb=8,
                          mixer_heads=2, mixer_depth=1, dtype="bfloat16"),
        replay=ReplayConfig(buffer_size=8),
    ))


def kernels_audit_config(attention: str = "xla"):
    """The frozen config for the KERNEL-MODE byte comparison
    (``train_iter_pallas``/``learner_train_pallas`` vs their ``_ref``
    einsum twins): the ``audit_config`` recipe at token counts where the
    attention logits tensor is material. At the shared tiny audit scale
    (3 AGVs, 7 tokens) the ``(S, R·H, T)`` logits the flash path
    eliminates are a few hundred bytes inside a ~2 MB program — the
    comparison would measure interpreter scaffolding, not the kernel.
    16 AGVs / 4 MECs / emb 16 puts the mixer attention at ~19 query
    rows × 2 heads against ~39 keys, where the eliminated forward
    logits + backward recompute dominate the mode delta and the
    lowered-level GP302 ratchet pins pallas STRICTLY below xla
    (tests/test_graftprog.py). Lowered level only — a compiled
    comparison on the CPU gate would measure the interpret-mode grid
    emulation (serial block copies the Mosaic lowering never performs),
    not the program structure."""
    from ..config import (EnvConfig, KernelsConfig, ModelConfig,
                          ReplayConfig, TrainConfig, sanity_check)
    return sanity_check(TrainConfig(
        batch_size_run=2, batch_size=4, superstep=AUDIT_SUPERSTEP_K,
        env_args=EnvConfig(agv_num=16, mec_num=4, num_channels=2,
                           episode_limit=6, fast_norm=False),
        model=ModelConfig(emb=16, heads=2, depth=1, mixer_emb=16,
                          mixer_heads=2, mixer_depth=1, dtype="bfloat16"),
        replay=ReplayConfig(buffer_size=8),
        kernels=KernelsConfig(attention=attention),
    ))


def sight_audit_config():
    """The frozen config for the graftsight-on twin entries
    (``train_iter_sight``/``superstep_sight`` — run.py's
    ``_sight_twin_programs``): ``audit_config`` with ONLY the static
    ``obs.sight.enabled`` gate flipped, so the twin-vs-base budget
    delta IS the in-graph diagnostic overhead and nothing else. Tiny
    bins keep the histogram scatters audit-scale."""
    import dataclasses as _dc

    from ..config import SightConfig
    cfg = audit_config()
    return cfg.replace(obs=_dc.replace(
        cfg.obs, sight=SightConfig(enabled=True, bins=8)))


def population_audit_config():
    """The frozen config for the graftpop twin entry (``superstep_pop``
    — run.py's ``_population_twin_programs``): ``audit_config`` with a
    FIXED P=2 population, so the twin-vs-base budget delta is the
    vmapped population axis and nothing else. The population-OFF
    fingerprints of every other entry are unaffected (the spec seams
    default to ``None``)."""
    from ..config import PopulationConfig
    cfg = audit_config()
    return cfg.replace(population=PopulationConfig(size=2))


def population_kernels_audit_config():
    """The frozen config for the vmap-over-pallas twin entry
    (``superstep_pop_pallas`` — run.py's ``_population_twin_programs``):
    ``kernels_audit_config("pallas")`` with a FIXED P=2 population, so
    the entry audits the flash kernels UNDER the population vmap at the
    kernel audit scale (token counts where the logits tensor the flash
    path eliminates is material — the tiny shared audit scale would
    measure scaffolding). Neither parent baseline moves: the
    population-OFF pallas fingerprints (``train_iter_pallas``) and the
    xla-mode population fingerprint (``superstep_pop``) are built from
    their own unchanged configs."""
    from ..config import PopulationConfig
    cfg = kernels_audit_config("pallas")
    return cfg.replace(population=PopulationConfig(size=2))


_pkctx: Optional[AuditContext] = None


def population_kernels_audit_context() -> AuditContext:
    """Build (once per process) the population×pallas audit context —
    the ``population_audit_context`` pattern: ``ts_shape`` is the
    ``(ts, spec)`` PAIR of stacked ``init_population`` avals."""
    global _pkctx
    with _ctx_lock:
        if _pkctx is None:
            import jax

            from .. import population as graftpop
            from ..run import Experiment
            cfg = population_kernels_audit_config()
            exp = Experiment.build(cfg)
            ts_shape = jax.eval_shape(
                lambda: graftpop.init_population(exp, cfg))
            _pkctx = AuditContext(cfg=cfg, exp=exp, ts_shape=ts_shape,
                                  superstep_k=AUDIT_SUPERSTEP_K)
        return _pkctx


_pctx: Optional[AuditContext] = None


def population_audit_context() -> AuditContext:
    """Build (once per process) the population audit context — the
    ``sight_audit_context`` caching pattern. ``ts_shape`` follows the
    context convention of being the aval the audit program takes: here
    the ``(ts, spec)`` PAIR of ``population.init_population`` avals —
    every leaf (P,)-STACKED — since ``superstep_pop`` consumes both
    (an unstacked TrainState aval would fail its vmap at trace time)."""
    global _pctx
    with _ctx_lock:
        if _pctx is None:
            import jax

            from .. import population as graftpop
            from ..run import Experiment
            cfg = population_audit_config()
            exp = Experiment.build(cfg)
            ts_shape = jax.eval_shape(
                lambda: graftpop.init_population(exp, cfg))
            _pctx = AuditContext(cfg=cfg, exp=exp, ts_shape=ts_shape,
                                 superstep_k=AUDIT_SUPERSTEP_K)
        return _pctx


_sctx: Optional[AuditContext] = None


def sight_audit_context() -> AuditContext:
    """Build (once per process) the sight-on audit context — the
    ``kernels_audit_context`` caching pattern."""
    global _sctx
    with _ctx_lock:
        if _sctx is None:
            import jax

            from ..run import Experiment
            cfg = sight_audit_config()
            exp = Experiment.build(cfg)
            ts_shape = jax.eval_shape(lambda: exp.init_train_state(
                cfg.seed))
            _sctx = AuditContext(cfg=cfg, exp=exp, ts_shape=ts_shape,
                                 superstep_k=AUDIT_SUPERSTEP_K)
        return _sctx


_kctx: Dict[str, AuditContext] = {}


def kernels_audit_context(attention: str) -> AuditContext:
    """Build (once per process, per kernel mode) the kernel-comparison
    audit context — same caching rationale as ``audit_context``; the
    run.py and learner hooks each consume both modes."""
    with _ctx_lock:
        if attention not in _kctx:
            import jax

            from ..run import Experiment
            cfg = kernels_audit_config(attention)
            exp = Experiment.build(cfg)
            ts_shape = jax.eval_shape(lambda: exp.init_train_state(
                cfg.seed))
            _kctx[attention] = AuditContext(
                cfg=cfg, exp=exp, ts_shape=ts_shape,
                superstep_k=AUDIT_SUPERSTEP_K)
        return _kctx[attention]


def audit_context(rebuild: bool = False) -> AuditContext:
    """Build (once per process) the shared audit context. Cached: the
    ``Experiment`` build pins the process-global PRNG impl and costs
    ~1 s, and every hook needs the same one for fingerprint stability."""
    global _ctx
    with _ctx_lock:
        if _ctx is None or rebuild:
            import jax

            from ..run import Experiment
            cfg = audit_config()
            exp = Experiment.build(cfg)
            ts_shape = jax.eval_shape(lambda: exp.init_train_state(cfg.seed))
            _ctx = AuditContext(cfg=cfg, exp=exp, ts_shape=ts_shape,
                                superstep_k=AUDIT_SUPERSTEP_K)
        return _ctx


def collect_default_programs() -> Registry:
    """Gather every registered program from the component hooks, in a
    stable order (run.py's driver programs, then the data-parallel,
    learner and serving surfaces). Each module names its own programs —
    the registry stays free of program-construction knowledge."""
    from .. import run as run_mod
    from ..envs import graftworld as graftworld_mod
    from ..kernels import attention as kernels_mod
    from ..learners import qmix_learner as learner_mod
    from ..parallel import mesh as mesh_mod
    from ..parallel import sebulba as sebulba_mod
    from ..serve import program as serve_mod

    reg: Registry = {}
    ctx = audit_context()
    for mod in (run_mod, mesh_mod, sebulba_mod, learner_mod, serve_mod,
                kernels_mod, graftworld_mod):
        hook = getattr(mod, "register_audit_programs", None)
        if hook is None:
            continue
        for name, prog in hook(ctx).items():
            if name in reg:
                raise ValueError(
                    f"audit program {name!r} registered twice "
                    f"({mod.__name__} collides with an earlier hook)")
            reg[name] = prog
    return reg


def required_audit_devices() -> int:
    """The host-device count the FULL default registry needs: the
    largest fixed audit mesh any hook builds. Baseline writes
    (``--write-programs``) refuse to run below this — a 2-device run
    would silently drop the 4-device pop_dp / sebulba / dp×mp entries
    from programs.json (the same silent-shrink bug class the ``--only``
    refusal from the graftprog CLI guards against)."""
    from ..parallel import mesh as mesh_mod
    from ..parallel import sebulba as sebulba_mod
    dpmp = 1
    for d in getattr(mesh_mod, "AUDIT_DPMP_MESH", ()):
        dpmp *= d
    return max(mesh_mod.AUDIT_MESH_DEVICES,
               sum(sebulba_mod.AUDIT_SPLIT), dpmp)


def collect_transfer_audits() -> Dict[str, TransferAudit]:
    """Gather every registered cross-mesh transfer from the component
    ``register_transfer_audits(ctx)`` hooks — today only the Sebulba
    params.sync publish, but the hook shape mirrors
    ``collect_default_programs`` so new publish paths (fleet hot param
    refresh, dp×mp resharding sync) register next to it."""
    from ..parallel import sebulba as sebulba_mod

    out: Dict[str, TransferAudit] = {}
    ctx = audit_context()
    for mod in (sebulba_mod,):
        hook = getattr(mod, "register_transfer_audits", None)
        if hook is None:
            continue
        for name, ta in hook(ctx).items():
            if name in out:
                raise ValueError(
                    f"transfer audit {name!r} registered twice")
            out[name] = ta
    return out


def load_programs_from(path_or_module: str) -> Registry:
    """Load extra programs from a module path or a ``.py`` file that
    defines ``register_audit_programs(ctx) -> dict`` — the seeded-
    regression entry point for the CLI tests (``--program-module``)."""
    import importlib
    import importlib.util

    if path_or_module.endswith(".py"):
        spec = importlib.util.spec_from_file_location(
            "_graftprog_extra", path_or_module)
        if spec is None or spec.loader is None:
            raise ValueError(f"cannot import {path_or_module!r}")
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
    else:
        mod = importlib.import_module(path_or_module)
    hook = getattr(mod, "register_audit_programs", None)
    if hook is None:
        raise ValueError(
            f"{path_or_module!r} defines no register_audit_programs")
    return dict(hook(audit_context()))
