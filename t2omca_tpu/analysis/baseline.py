"""Finding baseline: the accepted-findings ratchet for ``graftlint``.

The driver loop *deliberately* syncs at its cadence boundaries
(``run.run_sequential``: the stat flush, the run-ahead bound, resume),
and the host-RAM replay buffer *is* host code — those GL105 hits are
accepted, each with a one-line justification, in the checked-in
``analysis/baseline.json``. CI then enforces a ratchet: pre-existing
accepted findings never block, any NEW finding does (exit 1 from
``python -m t2omca_tpu.analysis``; ``scripts/lint.sh``).

Identity is ``Finding.key()`` = (rule, path, stripped code line) with a
count per key — line numbers churn with every unrelated edit, quoted
code text doesn't. When a file accrues *more* occurrences of an already
-baselined line (say a second copy-pasted ``device_get``), the excess
occurrences count as new.

The file is shared by two rule families: graftlint (``GL``) and
graftrace (``GT``). Each CLI leg diffs only its own family
(``filter_family``) — otherwise lint would report every GT entry as
stale and vice versa — and a ``--write-baseline`` from one leg carries
the other family's entries verbatim (the ``family=`` parameter of
``save_baseline``) instead of erasing them.
"""

from __future__ import annotations

import json
from collections import Counter
from pathlib import Path
from typing import Dict, List, Sequence, Tuple

from .graftlint import Finding

BASELINE_VERSION = 1
PROGRAMS_VERSION = 1

#: default checked-in location, next to this module
DEFAULT_BASELINE = Path(__file__).resolve().parent / "baseline.json"
#: compiled-program budgets/fingerprints (graftprog), same directory
DEFAULT_PROGRAMS = Path(__file__).resolve().parent / "programs.json"

Key = Tuple[str, str, str]          # (rule, path, code)


def load_baseline(path: Path = DEFAULT_BASELINE) -> Dict[Key, dict]:
    """baseline.json -> {key: {"count": n, "justification": str}}.
    A missing file is an empty baseline (fresh repos lint clean)."""
    path = Path(path)
    if not path.exists():
        return {}
    data = json.loads(path.read_text())
    if data.get("version") != BASELINE_VERSION:
        raise ValueError(
            f"baseline {path} has version {data.get('version')!r}, "
            f"this tool reads version {BASELINE_VERSION}")
    out: Dict[Key, dict] = {}
    for e in data["findings"]:
        key = (e["rule"], e["path"], e["code"])
        out[key] = {"count": int(e.get("count", 1)),
                    "justification": e.get("justification", "")}
    return out


def filter_family(baseline: Dict[Key, dict],
                  family: str) -> Dict[Key, dict]:
    """Restrict a loaded baseline to one rule family by id prefix
    (``"GL"`` for graftlint, ``"GT"`` for graftrace)."""
    return {k: v for k, v in baseline.items() if k[0].startswith(family)}


def save_baseline(path: Path, findings: Sequence[Finding],
                  old: Dict[Key, dict] | None = None,
                  family: str | None = None) -> None:
    """Write the current finding set as the new baseline, carrying over
    justifications for keys that survive; new keys get a TODO marker so
    review can't silently skip them.

    With ``family`` set (a rule-id prefix), the rewrite is scoped to
    that family: entries of OTHER families in ``old`` are carried
    verbatim — a ``--threads --write-baseline`` must never erase the
    lint entries sharing the file, and vice versa."""
    old = old or {}
    counts = Counter(f.key() for f in findings)
    entries = []
    for key in sorted(counts):
        rule, fpath, code = key
        entries.append({
            "rule": rule, "path": fpath, "code": code,
            "count": counts[key],
            "justification": old.get(key, {}).get(
                "justification") or "TODO: justify or fix",
        })
    if family is not None:
        for key in sorted(old):
            if not key[0].startswith(family):
                rule, fpath, code = key
                entries.append({
                    "rule": rule, "path": fpath, "code": code,
                    "count": old[key]["count"],
                    "justification": old[key].get("justification", ""),
                })
        entries.sort(key=lambda e: (e["rule"], e["path"], e["code"]))
    payload = {"version": BASELINE_VERSION, "findings": entries}
    Path(path).write_text(json.dumps(payload, indent=2) + "\n")


def diff_baseline(findings: Sequence[Finding],
                  baseline: Dict[Key, dict]
                  ) -> Tuple[List[Finding], List[Key]]:
    """-> (new_findings, stale_keys).

    New = occurrences beyond the baselined count for their key (the
    first ``count`` occurrences by line number are the accepted ones).
    Stale = baselined keys the code no longer produces — reported so the
    baseline can be re-written tight, but never a failure by themselves.
    """
    by_key: Dict[Key, List[Finding]] = {}
    for f in findings:
        by_key.setdefault(f.key(), []).append(f)
    new: List[Finding] = []
    for key, fs in sorted(by_key.items()):
        allowed = baseline.get(key, {}).get("count", 0)
        fs = sorted(fs, key=lambda f: (f.line, f.col))
        new.extend(fs[allowed:])
    stale = [k for k, e in sorted(baseline.items())
             if len(by_key.get(k, [])) < e["count"]]
    return sorted(new, key=lambda f: (f.path, f.line, f.col)), stale


# --------------------------------------------------- program baseline (GP)

def load_programs(path: Path = DEFAULT_PROGRAMS) -> dict:
    """programs.json -> {"platform": ..., "programs": {name: entry}}.
    A missing file is an empty baseline (every registered program then
    reports GP300 — new programs must be consciously accepted)."""
    path = Path(path)
    if not path.exists():
        return {"platform": None, "programs": {}, "transfers": {}}
    data = json.loads(path.read_text())
    if data.get("version") != PROGRAMS_VERSION:
        raise ValueError(
            f"programs baseline {path} has version "
            f"{data.get('version')!r}, this tool reads version "
            f"{PROGRAMS_VERSION}")
    return {"platform": data.get("platform"),
            "programs": dict(data.get("programs", {})),
            "transfers": dict(data.get("transfers", {}))}


def save_programs(path: Path, reports, platform: str,
                  old: dict | None = None) -> None:
    """Write the measured reports as the new program baseline. Same
    contract as ``save_baseline``: justifications and hand-tuned
    tolerances survive for entries that persist, new entries get a TODO
    marker and the default tolerances so review can't silently skip
    them. Skipped programs keep their previous entry untouched (a
    1-device host must not erase the dp budgets)."""
    from .graftprog import DEFAULT_TOLERANCE
    old_programs = (old or {}).get("programs", {})
    programs = {}
    for rep in sorted(reports, key=lambda r: r.name):
        prev = old_programs.get(rep.name, {})
        if rep.skipped is not None:
            if prev:
                programs[rep.name] = prev
            continue
        rules = {}
        for rule in sorted(rep.rule_details):
            n = rep.rule_count(rule)
            if n:
                rules[rule] = {
                    "count": n,
                    "justification": prev.get("rules", {}).get(rule, {})
                    .get("justification") or "TODO: justify or fix",
                }
        entry = {
            "fingerprint": rep.fingerprint,
            "level": rep.level,
            "flops": rep.flops,
            "bytes_accessed": rep.bytes_accessed,
            "tolerance": prev.get("tolerance", dict(DEFAULT_TOLERANCE)),
            "justification": prev.get("justification")
            or "TODO: justify or fix",
        }
        if rep.peak_bytes is not None:
            entry["peak_bytes"] = rep.peak_bytes
        if rules:
            entry["rules"] = rules
        # the comms section is measured by the OTHER audit level
        # (--comms, save_comms below) — a regular program-level rewrite
        # must carry it verbatim, not erase it
        if "comms" in prev:
            entry["comms"] = prev["comms"]
        programs[rep.name] = entry
    # entries for programs that no longer register at all are dropped
    # (the CLI's stale warning announced them); skipped ones survive
    payload = {"version": PROGRAMS_VERSION, "platform": platform,
               "programs": programs}
    transfers = (old or {}).get("transfers", {})
    if transfers:
        payload["transfers"] = transfers
    Path(path).write_text(json.dumps(payload, indent=2) + "\n")


def save_comms(path: Path, reports, transfers, platform: str,
               old: dict | None = None) -> None:
    """Write the measured comms census as the ``comms`` sections of the
    existing program entries plus the top-level ``transfers`` table —
    the ``save_baseline`` contract again: justifications and hand-tuned
    tolerances survive, new entries get a TODO marker, skipped audits
    keep their previous section untouched. Everything OUTSIDE the comms
    sections (fingerprints, budgets, GP2xx rules) is carried verbatim —
    the comms level must never perturb the program-level baseline."""
    from .graftshard import COMMS_TOLERANCE
    old = old or {"platform": platform, "programs": {}, "transfers": {}}
    programs = {n: dict(e) for n, e in old.get("programs", {}).items()}
    for rep in sorted(reports, key=lambda r: r.name):
        if rep.skipped is not None:
            continue
        entry = programs.setdefault(rep.name, {})
        prev = entry.get("comms", {})
        comms = {
            "mesh": rep.mesh,
            "collectives": {
                kind: {"count": e["count"], "bytes": e["bytes"],
                       "axes": list(e["axes"])}
                for kind, e in sorted(rep.census.items())},
            "bytes": rep.total_bytes,
            "tolerance": prev.get("tolerance", COMMS_TOLERANCE),
            "justification": prev.get("justification")
            or "TODO: justify or fix",
        }
        rules = {}
        for rule in sorted(rep.rule_details):
            n = rep.rule_count(rule)
            if n:
                rules[rule] = {
                    "count": n,
                    "justification": prev.get("rules", {}).get(rule, {})
                    .get("justification") or "TODO: justify or fix",
                }
        if rules:
            comms["rules"] = rules
        entry["comms"] = comms
    transfers_out = dict(old.get("transfers", {}))
    for rep in sorted(transfers, key=lambda r: r.name):
        if rep.skipped is not None:
            continue
        prev = transfers_out.get(rep.name, {})
        t = {
            "leaves": rep.leaves,
            "bytes": rep.bytes,
            "kind": rep.kind,
            "tolerance": prev.get("tolerance", COMMS_TOLERANCE),
            "justification": prev.get("justification")
            or "TODO: justify or fix",
        }
        rules = {}
        for rule in sorted(rep.rule_details):
            n = rep.rule_count(rule)
            if n:
                rules[rule] = {
                    "count": n,
                    "justification": prev.get("rules", {}).get(rule, {})
                    .get("justification") or "TODO: justify or fix",
                }
        if rules:
            t["rules"] = rules
        transfers_out[rep.name] = t
    payload = {"version": PROGRAMS_VERSION, "platform": platform,
               "programs": programs}
    if transfers_out:
        payload["transfers"] = transfers_out
    Path(path).write_text(json.dumps(payload, indent=2) + "\n")
