"""Finding baseline: the accepted-findings ratchet for ``graftlint``.

The driver loop *deliberately* syncs at its cadence boundaries
(``run.run_sequential``: the stat flush, the run-ahead bound, resume),
and the host-RAM replay buffer *is* host code — those GL105 hits are
accepted, each with a one-line justification, in the checked-in
``analysis/baseline.json``. CI then enforces a ratchet: pre-existing
accepted findings never block, any NEW finding does (exit 1 from
``python -m t2omca_tpu.analysis``; ``scripts/lint.sh``).

Identity is ``Finding.key()`` = (rule, path, stripped code line) with a
count per key — line numbers churn with every unrelated edit, quoted
code text doesn't. When a file accrues *more* occurrences of an already
-baselined line (say a second copy-pasted ``device_get``), the excess
occurrences count as new.
"""

from __future__ import annotations

import json
from collections import Counter
from pathlib import Path
from typing import Dict, List, Sequence, Tuple

from .graftlint import Finding

BASELINE_VERSION = 1

#: default checked-in location, next to this module
DEFAULT_BASELINE = Path(__file__).resolve().parent / "baseline.json"

Key = Tuple[str, str, str]          # (rule, path, code)


def load_baseline(path: Path = DEFAULT_BASELINE) -> Dict[Key, dict]:
    """baseline.json -> {key: {"count": n, "justification": str}}.
    A missing file is an empty baseline (fresh repos lint clean)."""
    path = Path(path)
    if not path.exists():
        return {}
    data = json.loads(path.read_text())
    if data.get("version") != BASELINE_VERSION:
        raise ValueError(
            f"baseline {path} has version {data.get('version')!r}, "
            f"this tool reads version {BASELINE_VERSION}")
    out: Dict[Key, dict] = {}
    for e in data["findings"]:
        key = (e["rule"], e["path"], e["code"])
        out[key] = {"count": int(e.get("count", 1)),
                    "justification": e.get("justification", "")}
    return out


def save_baseline(path: Path, findings: Sequence[Finding],
                  old: Dict[Key, dict] | None = None) -> None:
    """Write the current finding set as the new baseline, carrying over
    justifications for keys that survive; new keys get a TODO marker so
    review can't silently skip them."""
    old = old or {}
    counts = Counter(f.key() for f in findings)
    entries = []
    for key in sorted(counts):
        rule, fpath, code = key
        entries.append({
            "rule": rule, "path": fpath, "code": code,
            "count": counts[key],
            "justification": old.get(key, {}).get(
                "justification") or "TODO: justify or fix",
        })
    payload = {"version": BASELINE_VERSION, "findings": entries}
    Path(path).write_text(json.dumps(payload, indent=2) + "\n")


def diff_baseline(findings: Sequence[Finding],
                  baseline: Dict[Key, dict]
                  ) -> Tuple[List[Finding], List[Key]]:
    """-> (new_findings, stale_keys).

    New = occurrences beyond the baselined count for their key (the
    first ``count`` occurrences by line number are the accepted ones).
    Stale = baselined keys the code no longer produces — reported so the
    baseline can be re-written tight, but never a failure by themselves.
    """
    by_key: Dict[Key, List[Finding]] = {}
    for f in findings:
        by_key.setdefault(f.key(), []).append(f)
    new: List[Finding] = []
    for key, fs in sorted(by_key.items()):
        allowed = baseline.get(key, {}).get("count", 0)
        fs = sorted(fs, key=lambda f: (f.line, f.col))
        new.extend(fs[allowed:])
    stale = [k for k, e in sorted(baseline.items())
             if len(by_key.get(k, [])) < e["count"]]
    return sorted(new, key=lambda f: (f.path, f.line, f.col)), stale
