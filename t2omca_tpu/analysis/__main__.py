"""``python -m t2omca_tpu.analysis`` — the graftlint CLI.

Exit codes (the contract ``scripts/lint.sh`` and the tier-1 gate rely
on): 0 = no new findings (baselined accepted findings are fine),
1 = new findings (each printed as ``path:line:col: RULE message``),
2 = usage/internal error. Stale baseline entries are warned about but
never fail — re-run with ``--write-baseline`` to tighten the ratchet.

Deliberately jax-free: the lint pass is pure AST and runs in front of
every test batch, so it must not pay (or depend on) backend startup.
"""

from __future__ import annotations

import argparse
import sys
from collections import Counter
from pathlib import Path

from .baseline import (DEFAULT_BASELINE, diff_baseline, load_baseline,
                       save_baseline)
from .graftlint import RULES, lint_package


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m t2omca_tpu.analysis",
        description="graftlint: JAX tracing-hygiene static analysis "
                    "(rule catalog: docs/ANALYSIS.md)")
    parser.add_argument(
        "paths", nargs="*", type=Path,
        help="files/directories to lint (default: the t2omca_tpu package)")
    parser.add_argument(
        "--root", type=Path, default=None,
        help="repo root findings are reported relative to (default: the "
             "package's parent directory)")
    parser.add_argument(
        "--baseline", type=Path, default=DEFAULT_BASELINE,
        help="accepted-findings file (default: analysis/baseline.json)")
    parser.add_argument(
        "--no-baseline", action="store_true",
        help="report every finding as new (ignore the baseline)")
    parser.add_argument(
        "--write-baseline", action="store_true",
        help="accept the current finding set as the baseline (keeps "
             "existing justifications; new keys get a TODO marker)")
    parser.add_argument(
        "--list-rules", action="store_true",
        help="print the rule catalog and exit")
    args = parser.parse_args(argv)

    if args.list_rules:
        for rule, summary in sorted(RULES.items()):
            print(f"{rule}  {summary}")
        return 0

    root = args.root or Path(__file__).resolve().parents[2]
    try:
        findings = lint_package(root, args.paths or None)
    except (OSError, SyntaxError, ValueError) as e:
        print(f"graftlint: error: {e}", file=sys.stderr)
        return 2

    try:
        baseline = {} if args.no_baseline else load_baseline(args.baseline)
    except (OSError, ValueError, KeyError, TypeError) as e:
        print(f"graftlint: error: unreadable baseline {args.baseline}: "
              f"{e}", file=sys.stderr)
        return 2
    if args.write_baseline:
        save_baseline(args.baseline, findings, baseline)
        print(f"graftlint: wrote {len(set(f.key() for f in findings))} "
              f"accepted keys to {args.baseline}")
        return 0

    new, stale = diff_baseline(findings, baseline)
    for f in new:
        print(f.format())
        print(f"    {f.code}")
    for key in stale:
        rule, path, code = key
        print(f"graftlint: warning: stale baseline entry {rule} {path}: "
              f"{code!r} (fixed? run --write-baseline to tighten)",
              file=sys.stderr)
    n_base = len(findings) - len(new)
    per_rule = Counter(f.rule for f in new)
    summary = ", ".join(f"{r}x{c}" if c > 1 else r
                        for r, c in sorted(per_rule.items()))
    print(f"graftlint: {len(findings)} findings "
          f"({n_base} baselined, {len(new)} new"
          + (f": {summary}" if summary else "") + ")")
    return 1 if new else 0


if __name__ == "__main__":
    sys.exit(main())
