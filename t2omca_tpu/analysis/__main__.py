"""``python -m t2omca_tpu.analysis`` — the graftlint/graftrace/
graftprog/graftshard CLI.

Exit codes (the contract ``scripts/lint.sh``, ``scripts/t1.sh`` and the
tier-1 gate rely on): 0 = no new findings (baselined accepted findings
are fine), 1 = new findings (lint: ``path:line:col: RULE message``;
``--programs``/``--comms``: ``program: RULE message``), 2 =
usage/internal error. Stale baseline entries are warned about but never
fail — re-run with ``--write-baseline`` / ``--write-programs`` to
tighten the ratchet.

The default (lint) path is deliberately jax-free: pure AST, runs in
front of every test batch, must not pay backend startup. ``--programs``
is the opposite: it lowers (and for the donated hot programs compiles)
the registered XLA programs on a tiny CPU config — it forces
``JAX_PLATFORMS=cpu`` and a 4-CPU-device host platform so the audited
programs (and their checked-in fingerprints, ``analysis/programs.json``)
are identical on every machine, TPU hosts included. ``--comms`` is the
third level (graftshard, docs/ANALYSIS.md): it compiles the MESH-placed
registry programs under their fixed audit meshes and ratchets the
collective census + sharding rules (GP4xx) plus the registered
cross-mesh transfers against the same baseline file.
"""

from __future__ import annotations

import argparse
import os
import sys
from collections import Counter
from pathlib import Path

from .baseline import (DEFAULT_BASELINE, DEFAULT_PROGRAMS, diff_baseline,
                       filter_family, load_baseline, load_programs,
                       save_baseline, save_comms, save_programs)
from .graftlint import RULES, lint_package
from .graftrace import GT_RULES, trace_package


def _pin_cpu_platform() -> None:
    """Pin the audit to the canonical platform BEFORE jax initializes:
    CPU backend, and at least the 4 host devices the fixed audit meshes
    need (the dp program's 2-device data mesh, and the sebulba
    actor_step/learner_step programs' 2+2-device split). The checked-in
    fingerprints/budgets are for exactly this platform — auditing on
    whatever backend happens to be attached would produce fiction. A
    no-op when jax is already imported (in-process callers — the tests
    — own their platform)."""
    if "jax" in sys.modules:
        return
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=4").strip()


def _refuse_small_host(jax, registry, tool: str) -> int:
    """Baseline writes need every fixed audit mesh buildable: on a
    host exposing fewer devices than the largest registered mesh the
    4-device entries (pop_dp, sebulba, dp×mp) would register as skips
    and a rewrite would silently carry stale sections for them forever
    (the ``--only`` refusal's silent-shrink bug class, PR 5). 0 = ok."""
    need = registry.required_audit_devices()
    have = len(jax.devices())
    if have < need:
        print(f"{tool}: error: baseline writes need the full fixed "
              f"audit meshes: {need} host devices, have {have} (hint: "
              f"XLA_FLAGS=--xla_force_host_platform_device_count="
              f"{need}; unset any conflicting XLA_FLAGS)",
              file=sys.stderr)
        return 2
    return 0


def _comms_main(args) -> int:
    """The ``--comms`` audit level: collective census + sharding rules
    (GP4xx) of the mesh-placed registry programs and the registered
    cross-mesh transfers — graftshard (docs/ANALYSIS.md)."""
    if args.write_programs and args.only:
        print("graftshard: error: --write-programs re-baselines the "
              "FULL comms set; it cannot be combined with --only",
              file=sys.stderr)
        return 2
    _pin_cpu_platform()
    try:
        from . import graftshard, registry
        reg = registry.collect_default_programs()
        for extra in args.program_module:
            for name, prog in registry.load_programs_from(extra).items():
                reg[name] = prog
        reg = {n: p for n, p in reg.items()
               if graftshard.is_mesh_program(p)}
        transfers = registry.collect_transfer_audits()
    except Exception as e:  # noqa: BLE001 — CLI boundary
        print(f"graftshard: error: {type(e).__name__}: {e}",
              file=sys.stderr)
        return 2
    if args.only:
        unknown = [n for n in args.only
                   if n not in reg and n not in transfers]
        if unknown:
            print(f"graftshard: error: unknown mesh program(s) "
                  f"{', '.join(sorted(unknown))} (known: "
                  f"{', '.join(sorted(list(reg) + list(transfers)))})",
                  file=sys.stderr)
            return 2
        reg = {n: p for n, p in reg.items() if n in args.only}
        transfers = {n: t for n, t in transfers.items()
                     if n in args.only}
    if args.list_programs:
        for name, prog in reg.items():
            what = (f"SKIP ({prog.skip})" if prog.skip is not None else
                    prog.description)
            print(f"{name:16s} {'compile':8s} {what}")
        for name, ta in transfers.items():
            what = (f"SKIP ({ta.skip})" if ta.skip is not None else
                    ta.description)
            print(f"{name:16s} {'transfer':8s} {what}")
        return 0

    # resolve the old baseline BEFORE the compile-heavy audit — the
    # _programs_main fast-exit-2 rationale
    old = None
    if args.write_programs:
        try:
            old = load_programs(args.programs_baseline)
        except (OSError, ValueError, KeyError, TypeError) as e:
            print(f"graftshard: error: unreadable baseline "
                  f"{args.programs_baseline}: {e}", file=sys.stderr)
            return 2

    import jax
    if args.write_programs and (rc := _refuse_small_host(
            jax, registry, "graftshard")):
        return rc
    try:
        reports = graftshard.audit_comms_registry(reg)
        treports = [graftshard.audit_transfer(n, t)
                    for n, t in transfers.items()]
    except Exception as e:  # noqa: BLE001 — CLI boundary
        print(f"graftshard: error: {type(e).__name__}: {e}",
              file=sys.stderr)
        return 2

    if args.write_programs:
        save_comms(args.programs_baseline, reports, treports,
                   platform=jax.default_backend(), old=old or {})
        n = sum(r.skipped is None for r in reports)
        nt = sum(r.skipped is None for r in treports)
        print(f"graftshard: wrote {n} comms section(s) + {nt} "
              f"transfer entr{'y' if nt == 1 else 'ies'} to "
              f"{args.programs_baseline}")
        return 0

    if args.no_baseline:
        # raw audit: only the structural rules mean anything without a
        # baseline (GP401/402 are ratchets, like GP300-302)
        findings = graftshard.raw_findings(reports, treports)
        stale = [f"{r.name}: skipped ({r.skipped})"
                 for r in list(reports) + list(treports)
                 if r.skipped is not None]
    else:
        try:
            base = load_programs(args.programs_baseline)
        except (OSError, ValueError, KeyError, TypeError) as e:
            print(f"graftshard: error: unreadable baseline "
                  f"{args.programs_baseline}: {e}", file=sys.stderr)
            return 2
        platform = jax.default_backend()
        if base["platform"] and base["platform"] != platform:
            print(f"graftshard: warning: baseline is for platform "
                  f"{base['platform']!r}, running on {platform!r} — "
                  f"the comms census is not comparable, skipping the "
                  f"ratchet (pin JAX_PLATFORMS=cpu)", file=sys.stderr)
            return 0
        findings, stale = graftshard.compare_comms(reports, treports,
                                                   base)
    for f in findings:
        print(f.format())
    for note in stale:
        print(f"graftshard: warning: stale/skip: {note}",
              file=sys.stderr)
    per_rule = Counter(f.rule for f in findings)
    summary = ", ".join(f"{r}x{c}" if c > 1 else r
                        for r, c in sorted(per_rule.items()))
    n_skip = sum(r.skipped is not None
                 for r in list(reports) + list(treports))
    print(f"graftshard: {len(reports)} mesh programs + {len(treports)} "
          f"transfer(s) audited"
          + (f" ({n_skip} skipped)" if n_skip else "")
          + f", {len(findings)} new finding(s)"
          + (f": {summary}" if summary else ""))
    return 1 if findings else 0


def _programs_main(args) -> int:
    if args.write_programs and args.only:
        # save_programs writes exactly the audited set — a partial
        # audit would silently drop every unselected entry
        print("graftprog: error: --write-programs re-baselines the FULL "
              "program set; it cannot be combined with --only",
              file=sys.stderr)
        return 2
    _pin_cpu_platform()
    try:
        from . import graftprog, registry
        reg = registry.collect_default_programs()
        for extra in args.program_module:
            for name, prog in registry.load_programs_from(extra).items():
                reg[name] = prog
    except Exception as e:  # noqa: BLE001 — CLI boundary
        print(f"graftprog: error: {type(e).__name__}: {e}",
              file=sys.stderr)
        return 2
    if args.list_programs:
        for name, prog in reg.items():
            what = (f"SKIP ({prog.skip})" if prog.skip is not None else
                    prog.description)
            print(f"{name:16s} {'compile' if prog.compile else 'lower':8s}"
                  f" {what}")
        return 0

    # resolve the old baseline BEFORE the (minutes-long on a loaded
    # box) audit: a corrupt/version-mismatched programs.json must be a
    # fast exit-2 usage error, not a post-audit traceback
    old = None
    if args.write_programs and not args.no_baseline:
        try:
            old = load_programs(args.programs_baseline)
        except (OSError, ValueError, KeyError, TypeError) as e:
            print(f"graftprog: error: unreadable baseline "
                  f"{args.programs_baseline}: {e}", file=sys.stderr)
            return 2

    import jax
    if args.write_programs and (rc := _refuse_small_host(
            jax, registry, "graftprog")):
        return rc
    compute_dtype = registry.audit_context().compute_dtype
    try:
        reports = graftprog.audit_registry(
            reg, compute_dtype, only=args.only or None)
    except KeyError as e:
        print(f"graftprog: error: {e}", file=sys.stderr)
        return 2

    if args.write_programs:
        save_programs(args.programs_baseline, reports,
                      platform=jax.default_backend(), old=old or {})
        n = sum(r.skipped is None for r in reports)
        print(f"graftprog: wrote {n} program entries to "
              f"{args.programs_baseline}")
        return 0

    if args.no_baseline:
        # raw audit: every rule occurrence is a finding, budgets skipped
        findings = [graftprog.ProgFinding(r.name, rule, m)
                    for r in reports if r.skipped is None
                    for rule, msgs in sorted(r.rule_details.items())
                    for m in msgs]
        stale = [f"{r.name}: skipped ({r.skipped})"
                 for r in reports if r.skipped is not None]
    else:
        try:
            base = load_programs(args.programs_baseline)
        except (OSError, ValueError, KeyError, TypeError) as e:
            print(f"graftprog: error: unreadable baseline "
                  f"{args.programs_baseline}: {e}", file=sys.stderr)
            return 2
        platform = jax.default_backend()
        if base["platform"] and base["platform"] != platform:
            print(f"graftprog: warning: baseline is for platform "
                  f"{base['platform']!r}, running on {platform!r} — "
                  f"budgets/fingerprints are not comparable, skipping "
                  f"the ratchet (pin JAX_PLATFORMS=cpu)",
                  file=sys.stderr)
            return 0
        findings, stale = graftprog.compare_reports(reports,
                                                    base["programs"])
    for f in findings:
        print(f.format())
    for note in stale:
        print(f"graftprog: warning: stale/skip: {note}", file=sys.stderr)
    per_rule = Counter(f.rule for f in findings)
    summary = ", ".join(f"{r}x{c}" if c > 1 else r
                        for r, c in sorted(per_rule.items()))
    n_skip = sum(r.skipped is not None for r in reports)
    print(f"graftprog: {len(reports)} programs audited"
          + (f" ({n_skip} skipped)" if n_skip else "")
          + f", {len(findings)} new finding(s)"
          + (f": {summary}" if summary else ""))
    return 1 if findings else 0


def _ratchet_main(args, tool: str, family: str, run, root) -> int:
    """Shared source-ratchet leg: lint (GL) and threads (GT) differ only
    in the analyzer and the baseline family they own. ``run(root,
    paths)`` -> findings; exit 0/1/2 per the CLI contract."""
    try:
        findings = run(root, args.paths or None)
    except (OSError, SyntaxError, ValueError) as e:
        print(f"{tool}: error: {e}", file=sys.stderr)
        return 2

    try:
        full = {} if args.no_baseline else load_baseline(args.baseline)
    except (OSError, ValueError, KeyError, TypeError) as e:
        print(f"{tool}: error: unreadable baseline {args.baseline}: "
              f"{e}", file=sys.stderr)
        return 2
    if args.write_baseline:
        # scoped rewrite: the OTHER family's entries are carried verbatim
        save_baseline(args.baseline, findings, full, family=family)
        print(f"{tool}: wrote {len(set(f.key() for f in findings))} "
              f"accepted keys to {args.baseline}")
        return 0

    baseline = filter_family(full, family)
    new, stale = diff_baseline(findings, baseline)
    for f in new:
        print(f.format())
        print(f"    {f.code}")
    for key in stale:
        rule, path, code = key
        print(f"{tool}: warning: stale baseline entry {rule} {path}: "
              f"{code!r} (fixed? run --write-baseline to tighten)",
              file=sys.stderr)
    n_base = len(findings) - len(new)
    per_rule = Counter(f.rule for f in new)
    summary = ", ".join(f"{r}x{c}" if c > 1 else r
                        for r, c in sorted(per_rule.items()))
    print(f"{tool}: {len(findings)} findings "
          f"({n_base} baselined, {len(new)} new"
          + (f": {summary}" if summary else "") + ")")
    return 1 if new else 0


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m t2omca_tpu.analysis",
        description="graftlint: JAX tracing-hygiene static analysis "
                    "(rule catalog: docs/ANALYSIS.md)")
    parser.add_argument(
        "paths", nargs="*", type=Path,
        help="files/directories to lint (default: the t2omca_tpu package)")
    parser.add_argument(
        "--root", type=Path, default=None,
        help="repo root findings are reported relative to (default: the "
             "package's parent directory)")
    parser.add_argument(
        "--baseline", type=Path, default=DEFAULT_BASELINE,
        help="accepted-findings file (default: analysis/baseline.json)")
    parser.add_argument(
        "--no-baseline", action="store_true",
        help="report every finding as new (ignore the baseline)")
    parser.add_argument(
        "--write-baseline", action="store_true",
        help="accept the current finding set as the baseline (keeps "
             "existing justifications; new keys get a TODO marker)")
    parser.add_argument(
        "--threads", action="store_true",
        help="run the graftrace thread-topology & lock-discipline "
             "audit (GT1xx) instead of the tracing lint — same "
             "baseline file, same exit-code contract, still jax-free")
    parser.add_argument(
        "--list-rules", action="store_true",
        help="print the rule catalog and exit")
    prog_group = parser.add_argument_group(
        "compiled-program audit (graftprog, docs/ANALYSIS.md)")
    prog_group.add_argument(
        "--programs", action="store_true",
        help="audit the registered compiled programs (GP rules + HLO "
             "budgets) instead of linting source")
    prog_group.add_argument(
        "--comms", action="store_true",
        help="audit the communication structure of the mesh-placed "
             "programs: collective census + GP4xx sharding rules "
             "(graftshard; reuses --programs-baseline, "
             "--write-programs, --program-module, --only)")
    prog_group.add_argument(
        "--programs-baseline", type=Path, default=DEFAULT_PROGRAMS,
        help="program budgets/fingerprints file "
             "(default: analysis/programs.json)")
    prog_group.add_argument(
        "--write-programs", action="store_true",
        help="accept the measured budgets/fingerprints as the baseline "
             "(keeps justifications + tolerances; new entries get TODO)")
    prog_group.add_argument(
        "--program-module", action="append", default=[], metavar="MOD",
        help="extra module (dotted path or .py file) whose "
             "register_audit_programs(ctx) adds programs — the seeded-"
             "regression test entry point; repeatable")
    prog_group.add_argument(
        "--only", action="append", default=[], metavar="NAME",
        help="audit only the named program(s); repeatable")
    prog_group.add_argument(
        "--list-programs", action="store_true",
        help="print the registered program names and exit")
    args = parser.parse_args(argv)

    if args.list_rules:
        from .graftprog import GP_RULES
        from .graftshard import GP4_RULES
        for rule, summary in sorted({**RULES, **GT_RULES, **GP_RULES,
                                     **GP4_RULES}.items()):
            print(f"{rule}  {summary}")
        return 0
    if args.comms:
        return _comms_main(args)
    # the program-audit flags imply --programs: falling through to the
    # lint path would silently ignore them (a bare `--write-programs`
    # after an intended change would exit 0 having written nothing,
    # and the next gate run would fail GP304 with no hint why)
    if (args.programs or args.list_programs or args.write_programs
            or args.program_module or args.only):
        return _programs_main(args)

    root = args.root or Path(__file__).resolve().parents[2]
    if args.threads:
        return _ratchet_main(args, "graftrace", "GT", trace_package,
                             root)
    return _ratchet_main(args, "graftlint", "GL", lint_package, root)


if __name__ == "__main__":
    sys.exit(main())
