from .episode_runner import EpisodeRunner
from .parallel_runner import ParallelRunner, RolloutStats, RunnerState

RUNNER_REGISTRY = {"parallel": ParallelRunner, "episode": EpisodeRunner}

__all__ = ["ParallelRunner", "EpisodeRunner", "RunnerState", "RolloutStats",
           "RUNNER_REGISTRY"]
