"""Vectorized rollout runner — the TPU replacement for the subprocess farm.

Re-creates ``ParallelRunner`` (``/root/reference/parallel_runner.py:13-287``,
C3) with the Anakin/PureJaxRL pattern (SURVEY.md §7.1): instead of
``batch_size_run`` daemon processes exchanging pickled NumPy over Pipes, the
pure-functional env is ``jax.vmap``-ed over the env axis and ``lax.scan``-ed
over episode time, with MAC action selection fused into the same XLA program.
"runner↔env communication" is a function call inside one compiled program —
the entire IPC tier (``env_worker``, ``CloudpickleWrapper``, the five-message
Pipe protocol, ``:234-287``) has no equivalent because nothing crosses a
process boundary.

Semantics preserved:

* per-env independent streams: worker ``i`` gets ``seed + i`` (Q8) → here
  ``jax.random.split`` of a per-rollout key, one subkey per env lane;
* per-env Welford obs normalizers persist across episodes (reference: one
  per subprocess lifetime; here carried in ``RunnerState`` and threaded back
  into ``env.reset``) and update even in test mode (Q4);
* actions recorded into the episode at the pre-step slot (Q15);
* time-limit termination recorded as non-terminal for bootstrapping (Q7):
  ``terminated & ~info.episode_limit``;
* stats summed over envs and episodes, logged as ``<k>_mean = v/n`` with the
  same keys (``parallel_runner.py:202-231``, §5.5 metric contract);
* epsilon logged from the selector schedule (``:217-218``).

The env in this build terminates only at ``episode_limit``, so every lane
runs exactly ``T`` slots and ``filled`` is all-ones — the general masks are
still produced for parity with the M4 scheme.
"""

from __future__ import annotations

import dataclasses
from typing import Dict

import jax
import jax.numpy as jnp
from flax import struct

from ..components.episode_buffer import CompactEntityObs, TimeMajorEpisodes
from ..config import TrainConfig
from ..controllers.basic_mac import BasicMAC
from ..envs.mec_offload import EnvParams, EnvState, MultiAgvOffloadingEnv
from ..envs.normalization import (RewardScaleState, reset_reward_scale,
                                  scale_reward)
from ..envs.registry import make_scenario_distribution

#: fold_in salt for the per-rollout scenario-sampling key: the sampler
#: key is folded OFF the rollout key, never split from it — splitting
#: would re-pair the threefry counters of the existing reset/scan split
#: and silently change every env stream even for the fixed default
#: scenario (the graftworld bit-parity contract, tests/test_graftworld.py)
_SCENARIO_SALT = 0x5CE7


@struct.dataclass
class RunnerState:
    """Cross-episode carried state (one vmap lane = one reference worker)."""

    env_states: EnvState      # batched (B, ...) — holds the persistent norms
    key: jnp.ndarray          # PRNG key
    t_env: jnp.ndarray        # () int32 — global env-step cursor
    # per-lane reward-scaling state (envs/normalization.RewardScaleState;
    # active only under env_args.reward_scaling, but always carried so the
    # checkpoint pytree is config-independent)
    rscale: RewardScaleState
    # per-lane scenario instances (graftworld EnvParams, batched (B, ...)):
    # the knobs the CURRENT episode of each lane runs under, resampled
    # from the config's ScenarioDistribution at every rollout start.
    # Carried so (a) checkpoints record the active scenarios, (b) the
    # data-parallel/sebulba placement rules shard them with their lanes
    # (parallel/mesh.py, parallel/sebulba.py)
    env_params: EnvParams


@struct.dataclass
class RolloutStats:
    """Per-rollout stats with the reference's terminal-info semantics
    (``/root/reference/parallel_runner.py:168-170,226-231``): the logged
    ``<k>_mean`` keys aggregate the info dict of the TERMINAL step only
    (the reference collects ``final_env_infos`` at termination and sums
    those), not per-step sums. All info fields here are the terminal-step
    values per env lane; ``episode_return``/``episode_length`` feed
    ``return_mean`` and ETA accounting."""

    episode_return: jnp.ndarray            # (B,) summed reward (return_mean)
    episode_length: jnp.ndarray            # (B,)
    reward: jnp.ndarray                    # (B,) terminal-step values below
    delay_reward: jnp.ndarray              # (B,)
    overtime_penalty: jnp.ndarray          # (B,)
    channel_utilization_rate: jnp.ndarray  # (B,)
    conflict_ratio: jnp.ndarray            # (B,)
    episode_limit: jnp.ndarray             # (B,) terminated-by-time-limit
    task_completion_rate: jnp.ndarray      # (B,)
    task_completion_delay: jnp.ndarray     # (B,)
    deadline_miss_rate: jnp.ndarray        # (B,)
    epsilon: jnp.ndarray                   # ()
    # per-lane scenario-family tag (graftworld): which family slice each
    # episode ran under — the stats accumulators group the terminal-info
    # aggregation by it (per-slice generalization eval, utils/stats.py)
    scenario: jnp.ndarray                  # (B,) int32


@dataclasses.dataclass(frozen=True)
class ParallelRunner:
    env: MultiAgvOffloadingEnv
    mac: BasicMAC
    cfg: TrainConfig

    @property
    def batch_size(self) -> int:
        return self.cfg.batch_size_run

    @property
    def compact_store(self) -> bool:
        """Store the factored entity obs instead of the flattened tensor
        (ops/query_slice.entity_store_eligible)."""
        from ..ops.query_slice import entity_store_eligible
        return entity_store_eligible(self.cfg)

    def get_env_info(self) -> Dict[str, int]:
        return self.env.get_env_info()

    @property
    def scenario(self):
        """The config's scenario distribution (graftworld) — a frozen,
        hashable dataclass the jitted rollout closes over as static
        structure; built on demand (cheap: pure dataclass assembly)."""
        return make_scenario_distribution(self.cfg.env_args)

    def _sample_scenarios(self, key: jax.Array,
                          member=None) -> EnvParams:
        """One EnvParams instance per lane, from a ``fold_in`` side key
        (see ``_SCENARIO_SALT``): each lane draws its own scenario with
        zero extra dispatches — the sampling is part of the rollout
        program. ``member`` (a traced graftpop member index, only under
        ``population.scenario_salt``) folds a per-member salt into the
        sampler key so vmapped members draw different scenario
        instances from the same distribution
        (envs/graftworld.member_scenario_key); ``None`` keeps the
        pre-population key chain bit-identical."""
        scn = self.scenario
        k = jax.random.fold_in(key, _SCENARIO_SALT)
        if member is not None:
            from ..envs.graftworld import member_scenario_key
            k = member_scenario_key(k, member)
        keys = jax.random.split(k, self.batch_size)
        return jax.vmap(lambda k: scn.sample(k, self.env))(keys)

    # ------------------------------------------------------------------ state

    def init_state(self, key: jax.Array) -> RunnerState:
        """Initial env states; norms start fresh (as at subprocess spawn).
        ``env_args.seed`` is folded into the key chain (Q8: the reference
        hands worker ``i`` ``seed + i``; here one fold_in re-seeds the whole
        per-lane split, so two configs differing only in env seed roll
        different worlds)."""
        key = jax.random.fold_in(key, self.cfg.env_args.seed)
        key, k_reset = jax.random.split(key)
        env_params = self._sample_scenarios(k_reset)
        states, *_ = jax.vmap(self.env.reset)(
            jax.random.split(k_reset, self.batch_size), None, env_params)
        return RunnerState(
            env_states=states, key=key,
            t_env=jnp.zeros((), jnp.int32),
            rscale=RewardScaleState.create(gamma=self.cfg.gamma,
                                           dim=self.batch_size),
            env_params=env_params)

    # ------------------------------------------------------------------ rollout

    def run(self, params, rs: RunnerState, test_mode: bool = False,
            capture: bool = False, eps_scale=None, member=None):
        """One synchronous batched episode. Pure → jittable; ``test_mode``
        (greedy selection) and ``capture`` are static Python bools.

        With ``capture=True`` a fourth return value carries the per-step
        visualization fields (pre-step AGV positions, serving MECs, ACKs) as
        ``(T, B, ...)`` arrays — the same scan emits them, so the trajectory
        is exactly the episode in the returned batch (no re-run, no drift)."""
        out = self.run_raw(params, rs, test_mode=test_mode, capture=capture,
                           eps_scale=eps_scale, member=member)
        if capture:
            new_rs, tm, stats, viz = out
            return new_rs, tm.to_batch(), stats, viz
        new_rs, tm, stats = out
        return new_rs, tm.to_batch(), stats

    def run_raw(self, params, rs: RunnerState, test_mode: bool = False,
                capture: bool = False, eps_scale=None, member=None):
        """``run`` minus the episode-batch assembly: returns the scan's
        time-major emission (``TimeMajorEpisodes``) so the fused superstep
        can scatter it straight into the replay ring without ever
        materializing the ``(B, T+1, ...)`` batch. ``run`` itself is
        ``run_raw`` + ``to_batch()`` — one rollout definition for both
        paths.

        ``eps_scale``/``member`` are the graftpop per-member seams
        (traced scalars from the PopulationSpec the population
        superstep vmaps over): the epsilon-schedule multiplier and the
        scenario-sampler member salt. ``None`` defaults keep every
        pre-population caller's program byte-identical."""
        b, t_len = self.batch_size, self.env.cfg.episode_limit
        key, k_reset, k_scan = jax.random.split(rs.key, 3)
        # qslice weight folds are loop-invariant: do them once per rollout,
        # not once per scan step (no-op on other acting paths)
        params = self.mac.prepare_acting_params(params)

        # graftworld: every lane samples a fresh scenario instance at
        # episode start (per-lane EnvParams, one traced program for the
        # whole distribution — fixed/uniform/mixture alike). The sampler
        # key folds off rs.key so the env/action key streams are
        # untouched (bit-parity at the fixed default scenario)
        env_params = self._sample_scenarios(rs.key, member=member)

        # reset every lane, carrying each lane's Welford normalizer (Q4)
        reset_keys = jax.random.split(k_reset, b)
        env_states, obs, gstate, avail = jax.vmap(self.env.reset)(
            reset_keys, rs.env_states.norm, env_params)

        hidden = self.mac.init_hidden(b)

        compact_store = self.compact_store
        sd = jnp.dtype(self.cfg.replay.store_dtype)

        def obs_store(env_states, obs, compact):
            """Pre-step observation in its storage form (Q15 slot). Compact
            leaves stay f32 even under store_dtype=bf16: they are raw
            UN-normalized features (O(1e4) data sizes), where bf16 error is
            amplified ~|mean|/std by the learner's re-normalization — and
            at ~1/20th the footprint of the dense obs there is nothing
            worth saving."""
            if not compact_store:
                return obs.astype(sd)
            rows, _, mean, std = compact
            return CompactEntityObs(
                rows=rows,
                mec_index=env_states.mec_index.astype(jnp.int8),
                mean=mean, std=std)

        # reward scaling (env_args.reward_scaling): the discounted-return
        # accumulator resets each episode, the running std persists (C2
        # RewardScaling semantics). Train rollouts only — eval batches are
        # never trained on, and updating the std from greedy episodes
        # would perturb the training scale across test cadences.
        scale_on = self.cfg.env_args.reward_scaling and not test_mode
        rscale0 = reset_reward_scale(rs.rscale)

        def step_fn(carry, key_t):
            env_states, obs, gstate, avail, hidden, t_env, rscale = carry
            k_act, k_env = jax.random.split(key_t)
            # entity-table acting / compact storage: the factored obs is a
            # pure function of the carried env state (same post-update norm
            # stats the carried obs was normalized with), so recompute it
            # here instead of widening the carry
            compact = (jax.vmap(self.env.compact_obs)(env_states,
                                                      env_params)
                       if self.mac.use_entity_tables or compact_store
                       else None)
            actions, hidden, eps = self.mac.select_actions(
                params, obs, avail, hidden, k_act, t_env,
                test_mode=test_mode, compact=compact, eps_scale=eps_scale)
            # Q15: the action is recorded with the pre-step observation.
            # Cast to the storage dtype here so the scan stacks the compact
            # representation (the f32 episode stack is the HBM hot spot);
            # avail narrows to bool — it is a predicate, and bool storage
            # makes arithmetic misuse a type error
            pre = (obs_store(env_states, obs, compact), gstate.astype(sd),
                   avail > 0, actions)
            viz = ((env_states.pos, env_states.mec_index)
                   if capture else None)
            env_states, reward, terminated, info, obs, gstate, avail = \
                jax.vmap(self.env.step)(
                    env_states, actions, jax.random.split(k_env, b),
                    env_params)
            if scale_on:
                rscale, rec_reward = scale_reward(rscale, reward)
            else:
                rec_reward = reward
            env_terminal = terminated & ~info.episode_limit        # Q7
            ys = (pre, reward, rec_reward, env_terminal, info, eps,
                  (viz + (env_states.last_ack,)) if capture else ())
            t_env = t_env + jnp.where(jnp.asarray(test_mode), 0, b)
            return (env_states, obs, gstate, avail, hidden, t_env,
                    rscale), ys

        carry = (env_states, obs, gstate, avail, hidden, rs.t_env, rscale0)
        carry, ys = jax.lax.scan(step_fn, carry, jax.random.split(k_scan, t_len))
        env_states, last_obs, last_gstate, last_avail, _, t_env, rscale = carry
        (pre, reward, rec_reward, env_terminal, info, eps, viz_seq) = ys
        obs_seq, gstate_seq, avail_seq, action_seq = pre

        if compact_store:
            last_obs_store = obs_store(
                env_states, last_obs,
                jax.vmap(self.env.compact_obs)(env_states, env_params))
        else:
            last_obs_store = last_obs.astype(sd)
        tm = TimeMajorEpisodes(
            obs=obs_seq,
            state=gstate_seq,
            avail_actions=avail_seq,
            actions=action_seq,
            reward=rec_reward,       # scaled under reward_scaling; else raw
            terminated=env_terminal,
            last_obs=last_obs_store,
            last_state=last_gstate.astype(sd),
            last_avail=last_avail > 0,
        )

        last = lambda x: x[-1]             # terminal-step info values
        stats = RolloutStats(
            episode_return=reward.sum(axis=0),
            episode_length=jnp.full((b,), t_len, jnp.float32),
            reward=last(reward),
            delay_reward=last(info.delay_reward),
            overtime_penalty=last(info.overtime_penalty),
            channel_utilization_rate=last(info.channel_utilization_rate),
            conflict_ratio=last(info.conflict_ratio),
            episode_limit=last(info.episode_limit).astype(jnp.float32),
            task_completion_rate=last(info.task_completion_rate),
            task_completion_delay=last(info.task_completion_delay),
            deadline_miss_rate=last(info.deadline_miss_rate),
            epsilon=eps[-1],
            scenario=env_params.family,
        )
        new_rs = RunnerState(env_states=env_states, key=key, t_env=t_env,
                             rscale=rscale if scale_on else rs.rscale,
                             env_params=env_params)
        if capture:
            pos_seq, mec_seq, ack_seq = viz_seq
            viz = {"pos": pos_seq, "mec_index": mec_seq, "acks": ack_seq,
                   "actions": action_seq, "reward": reward, "info": info}
            return new_rs, tm, stats, viz
        return new_rs, tm, stats
