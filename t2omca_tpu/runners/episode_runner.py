"""Single-env episode runner (M5) — evaluation, animation, benchmark export.

The reference delegates render/animation/benchmark runs to a single-env
``EpisodeRunner`` clone (``/root/reference/parallel_runner.py:49-52,104-105``;
contract in SURVEY.md §2.3 M5: ``run(test_mode, render, save_animation,
benchmark_mode)`` returning per-episode info dicts, plus ``save_replay`` /
``save_animation``).

TPU design: rather than a host-side Python step loop with live matplotlib
rendering, the episode runs as the same fused scan as ``ParallelRunner`` with
``B = 1``, and the *same scan* emits the visualization trajectory (AGV
positions, serving MECs, ACKs) as extra scan outputs — so the exported
trajectory is exactly the episode whose batch/stats are returned. One device
program + one host drawing pass instead of ``episode_limit`` alternations.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Dict, List, Optional

import jax
import numpy as np

from ..config import TrainConfig
from ..controllers.basic_mac import BasicMAC
from ..envs.mec_offload import MultiAgvOffloadingEnv
from .parallel_runner import ParallelRunner, RunnerState


@dataclasses.dataclass(frozen=True)
class EpisodeRunner:
    """Batch-1 runner reusing the ParallelRunner program, plus viz capture."""

    env: MultiAgvOffloadingEnv
    mac: BasicMAC
    cfg: TrainConfig

    def __post_init__(self):
        object.__setattr__(
            self, "_inner",
            ParallelRunner(self.env, self.mac,
                           self.cfg.replace(batch_size_run=1)))

    @property
    def batch_size(self) -> int:
        return 1

    def get_env_info(self) -> Dict[str, int]:
        return self.env.get_env_info()

    def init_state(self, key: jax.Array) -> RunnerState:
        return self._inner.init_state(key)

    # ------------------------------------------------------------------ run

    def run(self, params, rs: RunnerState, test_mode: bool = True,
            capture_trajectory: bool = False):
        """→ (rs', batch, stats[, trajectory]). ``trajectory`` is a host-side
        dict of per-step arrays for rendering/benchmark export, emitted by
        the same scan that produced ``batch`` (no re-run, no drift)."""
        if not capture_trajectory:
            return self._inner.run(params, rs, test_mode=test_mode)
        rs2, batch, stats, viz = self._inner.run(
            params, rs, test_mode=test_mode, capture=True)
        return rs2, batch, stats, self._to_host(viz)

    def _to_host(self, viz) -> Dict[str, np.ndarray]:
        """Device ``(T, B=1, ...)`` viz pytree → host dict of ``(T, ...)``."""
        viz = jax.device_get(viz)
        info = viz["info"]
        lane = lambda x: np.asarray(x)[:, 0]
        return {
            "pos": lane(viz["pos"]),
            "mec_index": lane(viz["mec_index"]),
            "actions": lane(viz["actions"]),
            "acks": lane(viz["acks"]),
            "reward": lane(viz["reward"]),
            "delay_reward": lane(info.delay_reward),
            "overtime_penalty": lane(info.overtime_penalty),
            "channel_utilization_rate": lane(info.channel_utilization_rate),
            "conflict_ratio": lane(info.conflict_ratio),
            "task_completion_rate": lane(info.task_completion_rate),
            "task_completion_delay": lane(info.task_completion_delay),
            "deadline_miss_rate": lane(info.deadline_miss_rate),
            "mec_positions": np.asarray(self.env.mec_positions()),
            "radius": np.asarray(self.env.cfg.communication_range_m),
        }

    # ------------------------------------------------------------------ export

    @staticmethod
    def save_replay(traj: Dict[str, np.ndarray], path: str) -> str:
        """Replay = the recorded trajectory arrays (npz). Reference
        ``save_replay`` hook (``parallel_runner.py:68-69``)."""
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        np.savez_compressed(path, **{k: v for k, v in traj.items()})
        return path

    @staticmethod
    def save_animation(traj: Dict[str, np.ndarray], path: str,
                       fps: int = 10) -> Optional[str]:
        """Render the MEC deployment + AGV teleport trajectory to a gif
        (capability of ``draw_mec_deployment``/``save_animation``,
        ``environment_multi_mec.py:447-471``, ``parallel_runner.py:70-72``)."""
        try:
            import matplotlib
            matplotlib.use("Agg")
            import matplotlib.pyplot as plt
            from matplotlib import animation
        except Exception:   # matplotlib absent → gracefully skip (env gate)
            return None

        mecs, r = traj["mec_positions"], float(traj["radius"])
        fig, ax = plt.subplots(figsize=(8, 4))
        for (x, y) in mecs:
            ax.add_patch(plt.Circle((x, y), r, fill=False, ls="--"))
            ax.plot([x], [y], marker="s", ms=8)
        scat = ax.scatter(traj["pos"][0, :, 0], traj["pos"][0, :, 1])
        ax.set_xlim(-r, mecs[:, 0].max() + r)
        ax.set_ylim(-r, 3 * r)
        ax.set_aspect("equal")

        def update(i):
            scat.set_offsets(traj["pos"][i])
            colors = np.where(traj["acks"][i] == -1, "red",
                              np.where(traj["acks"][i] == 1, "green", "gray"))
            scat.set_color(colors)
            ax.set_title(f"slot {i}  reward {traj['reward'][i]:.1f}")
            return (scat,)

        anim = animation.FuncAnimation(
            fig, update, frames=len(traj["pos"]), blit=False)
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        anim.save(path, writer=animation.PillowWriter(fps=fps))
        plt.close(fig)
        return path

    @staticmethod
    def benchmark_csv(trajs: List[Dict[str, np.ndarray]],
                      path: str) -> Optional[str]:
        """Benchmark-mode episode export to CSV (reference writes per-episode
        CSVs via pandas, ``/root/reference/per_run.py:96-101``). Gated on
        pandas availability like the animation path is on matplotlib."""
        try:
            import pandas as pd
        except Exception:
            return None

        rows = []
        for ep, traj in enumerate(trajs):
            rows.append({
                "episode": ep,
                "return": float(traj["reward"].sum()),
                "delay_reward": float(traj["delay_reward"].sum()),
                "overtime_penalty": float(traj["overtime_penalty"].sum()),
                "channel_utilization_rate":
                    float(traj["channel_utilization_rate"].mean()),
                "conflict_ratio": float(traj["conflict_ratio"].mean()),
                "task_completion_rate":
                    float(traj["task_completion_rate"][-1]),
                "task_completion_delay":
                    float(traj["task_completion_delay"][-1]),
                "deadline_miss_rate":
                    float(traj.get("deadline_miss_rate",
                                   np.zeros(1))[-1]),
            })
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        pd.DataFrame(rows).to_csv(path, index=False)
        return path
