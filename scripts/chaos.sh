#!/usr/bin/env bash
# Chaos soak runner (docs/RESILIENCE.md §5): cycle the fault-injection
# battery — hang at dispatch.superstep, transient + persistent dispatch
# failures, flaky checkpoint gather, crash mid-checkpoint, SIGTERM — for
# N iterations against the real driver on the CPU backend, asserting
# after every scenario that the run ended in a RESUMABLE state (a
# verify_checkpoint-passing checkpoint a fresh driver carries to t_max).
#
# The serve scenario (tests/test_fleet.py, docs/SERVING.md §fleet) runs
# in the same battery: an engine killed mid-burst plus an injected
# dispatch hang must end with ZERO hung requests (every admitted
# request completes or resolves SHED/deadline/error) and a RESUMABLE
# fleet — the quarantined engines restarted, rejoined, and serving a
# fresh request.
#
# The graftmorph elastic scenarios (tests/test_elastic.py,
# docs/RESILIENCE.md §6) cycle too: a failed preemption barrier must
# degrade to the per-host shard save and resume elastically — the
# coordinated-preemption exit path soaks alongside the dispatch
# faults it shares machinery with. The multi-host leg (chaos-marked in
# tests/test_multihost.py) SIGKILLs one of two real gloo processes and
# asserts the survivor exits 0 with a resumable checkpoint.
#
# Usage: bash scripts/chaos.sh [N]      (default N=3)
#
# Slow by design (each driver scenario is a full run() with fresh
# compiles; the serve scenario exports an artifact and runs the chaos
# traffic bench) — this is the soak gate for resilience PRs, not part
# of the tier-1 budget (tier-1 excludes them via `-m 'not slow'`).
set -o pipefail
N=${1:-3}
cd "$(dirname "$0")/.." || exit 2
for i in $(seq 1 "$N"); do
  echo "== chaos cycle $i/$N =="
  JAX_PLATFORMS=cpu python -m pytest tests/test_chaos.py tests/test_fleet.py \
    tests/test_elastic.py tests/test_multihost.py \
    -m chaos -q -p no:cacheprovider -p no:randomly || {
      echo "chaos cycle $i/$N FAILED — a fault scenario left the run "
      echo "unresumable (see the assertion above; docs/RESILIENCE.md §5)"
      exit 1
    }
done
echo "chaos soak passed: $N cycle(s), every scenario ended resumable"
