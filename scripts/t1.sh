#!/usr/bin/env bash
# Tier-1 verify gate — the ROADMAP.md command, verbatim. Run from the repo
# root: `bash scripts/t1.sh`. Prints DOTS_PASSED=<n> and exits with
# pytest's status.
set -o pipefail; bash "$(dirname "$0")/lint.sh"; lrc=$?; [ $lrc -ne 0 ] && { [ $lrc -eq 1 ] && echo "graftlint gate failed (new findings above; docs/ANALYSIS.md)" || echo "graftlint internal error (exit $lrc; docs/ANALYSIS.md)"; exit 1; }; rm -f /tmp/_t1.log; timeout -k 10 870 env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow' --continue-on-collection-errors -p no:cacheprovider -p no:xdist -p no:randomly 2>&1 | tee /tmp/_t1.log; rc=${PIPESTATUS[0]}; echo DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' /tmp/_t1.log | tr -cd . | wc -c); exit $rc
