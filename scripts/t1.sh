#!/usr/bin/env bash
# Tier-1 verify gate — the ROADMAP.md pytest command, fronted by the two
# static/compiled analysis preludes. Run from the repo root:
# `bash scripts/t1.sh`. Prints DOTS_PASSED=<n> and exits with pytest's
# status.
#
# Prelude 1 (graftlint, ~1 s): AST lint over the package; any NEW
# finding fails the gate before backend startup.
# Prelude 1a (graftrace, ~1 s): the concurrency sibling — GT1xx
# thread-topology / lock-discipline audit, same ratchet contract.
# Prelude 2 (graftprog, ~45 s budgeted at 240 s for a loaded box):
# lower/compile the registered hot programs and ratchet their
# donation/dtype/constant rules + HLO budgets + fingerprints against
# t2omca_tpu/analysis/programs.json. A wedged audit is a gate failure
# (timeout exit 124), not a silent skip.
#
# Both preludes pipe through tee for the log — hence pipefail +
# ${PIPESTATUS[0]}: without them tee's exit 0 swallows the gate status.
set -o pipefail
cd "$(dirname "$0")/.." || exit 2
bash scripts/lint.sh 2>&1 | tee /tmp/_t1_lint.log; lrc=${PIPESTATUS[0]}
[ $lrc -ne 0 ] && { [ $lrc -eq 1 ] && echo "graftlint gate failed (new findings above; docs/ANALYSIS.md)" || echo "graftlint internal error (exit $lrc; docs/ANALYSIS.md)"; exit 1; }
# Prelude 1a (graftrace, ~1 s, jax-free): thread-topology &
# lock-discipline audit (GT1xx) over the host concurrency plane —
# watchdog/fleet/sebulba/pulse threads. Same ratchet file, same
# contract: any NEW finding fails the gate before backend startup.
timeout -k 5 60 bash scripts/lint.sh --threads 2>&1 | tee /tmp/_t1_threads.log; trc=${PIPESTATUS[0]}
[ $trc -ne 0 ] && { [ $trc -eq 1 ] && echo "graftrace gate failed (new findings above; docs/ANALYSIS.md)" || echo "graftrace internal error (exit $trc; docs/ANALYSIS.md)"; exit 1; }
# Prelude 1b (obs timeline, ~1 s, jax-free): the longitudinal BENCH
# trajectory CLI over the checked-in records must exit 0 and render the
# r03+ wedged partials as wedged rows — the post-mortem tool must not
# rot while the TPU tunnel is down.
timeout -k 5 60 python -m t2omca_tpu.obs timeline BENCH_r*.json 2>&1 | tee /tmp/_t1_timeline.log; tlc=${PIPESTATUS[0]}
[ $tlc -ne 0 ] && { echo "obs timeline smoke failed (exit $tlc; docs/OBSERVABILITY.md §pulse)"; exit 1; }
grep -q "wedged" /tmp/_t1_timeline.log || { echo "obs timeline smoke: wedged BENCH rows missing from the table (docs/OBSERVABILITY.md §pulse)"; exit 1; }
# Prelude 1c (obs learning, ~1 s, jax-free): the graftsight learning-
# health CLI over the seeded fixture run dir must exit 0 and render the
# health table + detector verdict — the post-mortem learning read must
# not rot (docs/OBSERVABILITY.md §6).
timeout -k 5 60 python -m t2omca_tpu.obs learning tests/fixtures_sight_run 2>&1 | tee /tmp/_t1_sight.log; slc=${PIPESTATUS[0]}
[ $slc -ne 0 ] && { echo "obs learning smoke failed (exit $slc; docs/OBSERVABILITY.md §6)"; exit 1; }
grep -q "learning health" /tmp/_t1_sight.log || { echo "obs learning smoke: health table missing (docs/OBSERVABILITY.md §6)"; exit 1; }
grep -q "TRIPPED" /tmp/_t1_sight.log || { echo "obs learning smoke: seeded detector verdict missing (docs/OBSERVABILITY.md §6)"; exit 1; }
# JAX_PLATFORMS pinned HERE, not just inside the CLI: the CLI's own pin
# is a setdefault, and a preset JAX_PLATFORMS=tpu would otherwise make
# the audit hit the platform-mismatch branch (warn + exit 0) — a silent
# gate no-op
timeout -k 10 240 env JAX_PLATFORMS=cpu python -m t2omca_tpu.analysis --programs 2>&1 | tee /tmp/_t1_prog.log; prc=${PIPESTATUS[0]}
[ $prc -ne 0 ] && { [ $prc -eq 124 ] && echo "graftprog gate timed out (240s budget; docs/ANALYSIS.md)" || echo "graftprog gate failed (exit $prc; docs/ANALYSIS.md)"; exit 1; }
# Prelude 3 (graftshard, ~60 s budgeted at 180 s): compile the
# mesh-placed programs under the fixed audit meshes and ratchet their
# collective census + sharding rules (GP4xx) + the params.sync transfer
# table against the same programs.json. Same contract: a wedged comms
# audit is a gate failure (timeout exit 124), never a silent skip.
timeout -k 10 180 env JAX_PLATFORMS=cpu python -m t2omca_tpu.analysis --comms 2>&1 | tee /tmp/_t1_comms.log; crc=${PIPESTATUS[0]}
[ $crc -ne 0 ] && { [ $crc -eq 124 ] && echo "graftshard gate timed out (180s budget; docs/ANALYSIS.md)" || echo "graftshard gate failed (exit $crc; docs/ANALYSIS.md)"; exit 1; }
rm -f /tmp/_t1.log; timeout -k 10 870 env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow' --continue-on-collection-errors -p no:cacheprovider -p no:xdist -p no:randomly 2>&1 | tee /tmp/_t1.log; rc=${PIPESTATUS[0]}; echo DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' /tmp/_t1.log | tr -cd . | wc -c); exit $rc
