#!/usr/bin/env bash
# graftlint gate — JAX tracing-hygiene static analysis over t2omca_tpu/
# (rule catalog: docs/ANALYSIS.md; accepted findings + justifications:
# t2omca_tpu/analysis/baseline.json). Exit 0: no new findings; exit 1:
# new findings, each printed as path:line:col RULE message. Pure AST —
# no jax import, no backend startup — so it runs in front of the tier-1
# pytest batch (scripts/t1.sh) at negligible cost.
#
# `bash scripts/lint.sh --threads` runs the graftrace concurrency
# audit instead (GT1xx: thread topology + lock discipline over the
# host threads) — still pure AST/jax-free, same baseline file and the
# same 0/1/2 exit contract; t1.sh runs it as its own prelude.
#
# The same CLI also hosts the two compiled audit levels — `--programs`
# (graftprog: per-program HLO budgets/fingerprints, GP2xx/GP3xx) and
# `--comms` (graftshard: collective census + sharding rules, GP4xx) —
# which DO start a backend; t1.sh runs them as separate budgeted
# preludes rather than here.
#
# NB for callers: shell options do not propagate upward, so nothing in
# THIS script can protect `bash scripts/lint.sh | tee log` — the caller
# must own its pipe status (t1.sh uses `set -o pipefail` +
# ${PIPESTATUS[0]}). A bare `cmd | tee` reports tee's exit 0 and
# silently swallows the gate.
cd "$(dirname "$0")/.." || exit 2
python -m t2omca_tpu.analysis "$@"
