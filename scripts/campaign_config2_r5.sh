#!/bin/bash
# 16-AGV learning campaign, round 5: the loss-scale recipe at the
# REFERENCE'S OWN operating point.
#
# Point: agv_num=16, mec_num=2, num_channels=4 — the reference env's
# defaults (/root/reference/environment_multi_mec.py:10), which is the
# capability-match criterion of VERDICT r4 item 2. (Round 4's negative
# campaign — and this round's first attempt, captured as
# runs/config2_scaling/metrics_r5recipe_16agv4mec2ch_seed0_partial.jsonl
# — ran 16 AGVs x 4 MEC at the config-1 yaml's 2 channels: a harsher,
# non-reference point.) Model at d128 per BASELINE.json config 2.
#
# Random baseline at this point (scripts/random_baseline.py, 64 eps):
#   mean -44788, std 6382, conflict_ratio 0.63, completion 0.39
# => +2-sigma bar = -32024.
#
# Recipe (round-5 loss-scale fix, BASELINE.md "Round 5"):
#   reward_unit=100    per-step rewards O(1-5) in train units;
#   td_loss=huber d=10 storm outliers bounded, quadratic elsewhere;
#   mixer_zero_init    ReZero gate: kills the O(emb) init output scale
#                      (measured +-600 at emb=128) that made early
#                      bootstrap targets init noise.
# Everything else is the stable-sweep default set (lr 5e-4, eps floor 0.1).
# Recipe validated on config 1 first: seed 0 mean-last-3 = 7987 vs bar
# 7189, grad_norm tail O(10) vs the old 2e4-2e5
# (runs/config1_recipe/SUMMARY.md).
#
# Usage: nohup scripts/campaign_config2_r5.sh [outdir] [seeds...] &
#   T2OMCA_CAMPAIGN_EXTRA="action_selector=noisy-new"  adds an arm's
#   extra key=value overrides (the reference agent ships NoisyLinear and
#   its runner guards for non-epsilon selectors — per-agent noise is the
#   reference-faithful symmetry breaker for the 16-agent joint argmax).
set -u
cd "$(dirname "$0")/.."
OUT=${1:-/tmp/config2_r5}
shift || true
SEEDS=${@:-0 1 2}
EXTRA=${T2OMCA_CAMPAIGN_EXTRA:-}
mkdir -p "$OUT"
for s in $SEEDS; do
  echo "[campaign] seed $s start $(date -u +%FT%TZ)" >> "$OUT/campaign.log"
  PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu python -m t2omca_tpu.run train \
    --config configs/config1_cpu_parity.yaml \
    env_args.fast_norm=true env_args.agv_num=16 env_args.mec_num=2 \
    env_args.num_channels=4 \
    model.emb=128 model.mixer_emb=128 \
    reward_unit=100.0 td_loss=huber huber_delta=10.0 \
    model.mixer_zero_init=true \
    seed=$s save_model=false log_interval=2000 \
    local_results_path="$OUT/seed$s" \
    $EXTRA \
    >> "$OUT/seed${s}.log" 2>&1
  echo "[campaign] seed $s done rc=$? $(date -u +%FT%TZ)" >> "$OUT/campaign.log"
done
echo "[campaign] ALL DONE" >> "$OUT/campaign.log"
