#!/bin/bash
# Config-2 learning campaign, round 5: the loss-scale recipe.
#
# Round-4 root-cause (VERDICT r4 weak #2): grad_norm 2e4-2e5 against
# grad_norm_clip=10 — every update was a direction-only step, and the
# conflict-storm episodes (per-step reward O(-500)) dominated each MSE
# batch gradient. Recipe, three legs:
#   reward_unit=100    latency_max_ms — per-step rewards O(1-5) in train
#                      units, so clipping becomes inactive;
#   td_loss=huber d=10 storm outliers bounded, quadratic elsewhere;
#   mixer_zero_init    ReZero gate: the mixer's init output is O(emb)
#                      (measured +-600 at emb=128) — without the gate the
#                      early bootstrap targets are init noise 100x the
#                      unit-normalized reward signal.
# Everything else is the stable-sweep default set (lr 5e-4, eps floor 0.1).
# Recipe validated on config 1 first: seed 0 mean-last-3 = 7987 vs bar
# 7189, grad_norm tail O(10) vs the old 2e4-2e5.
#
# Usage: nohup scripts/campaign_config2_r5.sh [outdir] [seeds...] &
set -u
cd "$(dirname "$0")/.."
OUT=${1:-/tmp/config2_r5}
shift || true
SEEDS=${@:-0 1 2}
mkdir -p "$OUT"
for s in $SEEDS; do
  echo "[campaign] seed $s start $(date -u +%FT%TZ)" >> "$OUT/campaign.log"
  PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu python -m t2omca_tpu.run train \
    --config configs/config1_cpu_parity.yaml \
    env_args.fast_norm=true env_args.agv_num=16 env_args.mec_num=4 \
    model.emb=128 model.mixer_emb=128 \
    reward_unit=100.0 td_loss=huber huber_delta=10.0 \
    model.mixer_zero_init=true \
    seed=$s save_model=false log_interval=2000 \
    local_results_path="$OUT/seed$s" \
    >> "$OUT/seed${s}.log" 2>&1
  echo "[campaign] seed $s done rc=$? $(date -u +%FT%TZ)" >> "$OUT/campaign.log"
done
echo "[campaign] ALL DONE" >> "$OUT/campaign.log"
