"""Measure the uniform-random-legal-actions baseline for a config.

The learning gates (tests/test_learning_curve.py) compare a trained
policy's final evals against ``random_return_mean + 2*std`` — this script
produces that JSON for any scale point (the config-1 artifact
``runs/config1_full/random_baseline.json`` predates it; this is the
reproducible producer).

Usage:
    PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu python scripts/random_baseline.py \
        [--episodes 24] [--seed 0] [key=value config overrides...]
e.g. the config-2 point:
    ... scripts/random_baseline.py env_args.agv_num=16 env_args.mec_num=4 \
        env_args.num_channels=4
"""

import argparse
import json
import sys

import jax
import jax.numpy as jnp
import numpy as np

sys.path.insert(0, ".")

from t2omca_tpu.config import load_config  # noqa: E402
from t2omca_tpu.envs.registry import make_env  # noqa: E402


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--episodes", type=int, default=24)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("overrides", nargs="*")
    args = ap.parse_args()

    cfg = load_config(overrides=tuple(args.overrides))
    env = make_env(cfg.env_args)
    b, t_len = args.episodes, cfg.env_args.episode_limit

    def episode(key):
        k_reset, k_scan = jax.random.split(key)
        state, obs, gstate, avail = env.reset(k_reset)

        def body(carry, k):
            state, avail = carry
            k_act, k_step = jax.random.split(k)
            # uniform over LEGAL actions per agent (gumbel-max over the
            # avail mask — exact uniform on the legal set)
            g = jax.random.gumbel(k_act, avail.shape)
            actions = jnp.argmax(jnp.where(avail > 0, g, -jnp.inf), axis=-1)
            state, reward, _term, info, _obs, _gs, avail2 = env.step(
                state, actions, k_step)
            return (state, avail2), (reward, info.conflict_ratio,
                                     info.task_completion_rate)

        keys = jax.random.split(k_scan, t_len)
        _, (rew, cr, tcr) = jax.lax.scan(body, (state, avail), keys)
        return rew.sum(), cr[-1], tcr[-1]

    keys = jax.random.split(jax.random.PRNGKey(args.seed), b)
    rets, crs, tcrs = jax.jit(jax.vmap(episode))(keys)
    rets = np.asarray(rets)
    out = {
        "random_return_mean": float(rets.mean()),
        "random_return_std": float(rets.std()),
        "random_task_completion_rate": float(np.asarray(tcrs).mean()),
        "random_conflict_ratio": float(np.asarray(crs).mean()),
        "episodes": b,
    }
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    sys.exit(main())
