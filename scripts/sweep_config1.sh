#!/bin/bash
# Reproduces the config-1 learning-stability sweep
# (runs/config1_stable/SUMMARY.md): 5 seeds, full horizon, full fast
# stack, current default hypers. ~6 min/seed on one CPU core.
set -e
OUT=${1:-/tmp/config1_sweep}
for s in 0 1 2 3 4; do
  PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu python -m t2omca_tpu.run train \
    --config configs/config1_cpu_parity.yaml \
    env_args.fast_norm=true seed=$s save_model=false \
    local_results_path=$OUT/seed$s
  echo "seed $s done"
done
python - <<'PY'
import glob, json, os, sys
import numpy as np
out = os.environ.get("OUT", "/tmp/config1_sweep")
for s in range(5):
    for p in glob.glob(f"{out}/seed{s}/qmix*/metrics.jsonl"):
        rows = [json.loads(l) for l in open(p)]
        tr = [r["value"] for r in rows if r["key"] == "test_return_mean"]
        print(f"seed {s}: mean(last3 test_return) = {np.mean(tr[-3:]):.0f}")
PY
