#!/bin/bash
# TPU bench watcher: retry `bench.py --all` until it lands real numbers.
#
# The axon tunnel wedges if a client is killed mid-compile or if two
# processes race for the device claim (BASELINE.md axon note). So:
#   - exactly ONE process touches the TPU at a time (this loop, serial);
#   - never kill the bench; its own probe bound (900 s default) handles a
#     wedged init by emitting a parseable error record and exiting;
#   - on failure, cool down before the next attempt so a stale remote
#     claim can expire.
#
# Usage: nohup scripts/tpu_bench_watcher.sh [outdir] &
set -u
OUT=${1:-/tmp/tpu_bench}
mkdir -p "$OUT"
COOLDOWN=${T2OMCA_WATCHER_COOLDOWN:-600}
N=0
while :; do
  N=$((N + 1))
  LOG="$OUT/attempt_$N.log"
  echo "[watcher] attempt $N at $(date -u +%FT%TZ)" >> "$OUT/watcher.log"
  python bench.py --all > "$LOG" 2>&1
  RC=$?
  if grep -q '"value": *[0-9]' "$LOG"; then
    echo "[watcher] SUCCESS on attempt $N (rc=$RC)" >> "$OUT/watcher.log"
    cp "$LOG" "$OUT/SUCCESS.log"
    break
  fi
  echo "[watcher] attempt $N failed (rc=$RC); cooling down ${COOLDOWN}s" \
    >> "$OUT/watcher.log"
  sleep "$COOLDOWN"
done
