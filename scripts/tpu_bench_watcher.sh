#!/bin/bash
# TPU bench watcher: retry `bench.py --all` until it lands real numbers.
#
# The axon tunnel wedges if a client is killed mid-compile or if two
# processes race for the device claim (BASELINE.md axon note). So:
#   - exactly ONE process touches the TPU at a time (this loop, serial);
#   - never kill the bench; its own probe bound (300 s here, see the
#     T2OMCA_BACKEND_PROBE_TIMEOUT export below) handles a
#     wedged init by emitting a parseable error record and exiting;
#   - on failure, cool down before the next attempt so a stale remote
#     claim can expire.
#
# Usage: nohup scripts/tpu_bench_watcher.sh [outdir] &
set -u
cd "$(dirname "$0")/.."           # bench.py lives at the repo root
PYTHON=${PYTHON:-python}
command -v "$PYTHON" > /dev/null || PYTHON=python3
OUT=${1:-/tmp/tpu_bench}
mkdir -p "$OUT"
COOLDOWN=${T2OMCA_WATCHER_COOLDOWN:-600}
MAX_COOLDOWN=${T2OMCA_WATCHER_MAX_COOLDOWN:-3600}
# short probe bound: a healthy init is seconds; a wedged one never
# completes, and the hanging init itself holds a half-open claim that
# may prolong the wedge — touch the tunnel briefly, then back off
export T2OMCA_BACKEND_PROBE_TIMEOUT=${T2OMCA_BACKEND_PROBE_TIMEOUT:-300}
N=0
SLEEP=$COOLDOWN
while :; do
  N=$((N + 1))
  LOG="$OUT/attempt_$N.log"
  echo "[watcher] attempt $N at $(date -u +%FT%TZ)" >> "$OUT/watcher.log"
  "$PYTHON" bench.py --all > "$LOG" 2>&1
  RC=$?
  # full success only: rc==0 (bench_all ran every leg; per-leg failures
  # are caught internally and noted on stderr) AND a real numeric value
  # landed. A crash after a partial emit (rc!=0) must keep retrying.
  if [ "$RC" -eq 0 ] && grep -q '"value": *[0-9]' "$LOG"; then
    echo "[watcher] SUCCESS on attempt $N (rc=$RC)" >> "$OUT/watcher.log"
    cp "$LOG" "$OUT/SUCCESS.log"
    break
  fi
  # a deterministic post-headline hard crash (rc!=0, but the headline
  # value landed — bench_all emits most-important-first for exactly this
  # case) must not loop forever: accept the partial set after 3 tries
  if [ "$N" -ge 3 ] && grep -q '"value": *[0-9]' "$LOG"; then
    echo "[watcher] PARTIAL accepted on attempt $N (rc=$RC)" \
      >> "$OUT/watcher.log"
    cp "$LOG" "$OUT/PARTIAL.log"
    break
  fi
  # exponential backoff on wedged-probe failures (longer quiet periods
  # give the remote claim time to clear); reset on any other failure
  if grep -q "probe bound" "$LOG"; then
    SLEEP=$((SLEEP * 2)); [ "$SLEEP" -gt "$MAX_COOLDOWN" ] && SLEEP=$MAX_COOLDOWN
  else
    SLEEP=$COOLDOWN
  fi
  echo "[watcher] attempt $N failed (rc=$RC); cooling down ${SLEEP}s" \
    >> "$OUT/watcher.log"
  sleep "$SLEEP"
done
