#!/bin/bash
# TPU bench watcher: retry `bench.py --all` until it lands real numbers.
#
# The axon tunnel wedges if a client is killed mid-compile or if two
# processes race for the device claim (BASELINE.md axon note). So:
#   - exactly ONE process touches the TPU at a time (this loop, serial);
#   - never kill the bench; its own probe bound (900 s default) handles a
#     wedged init by emitting a parseable error record and exiting;
#   - on failure, cool down before the next attempt so a stale remote
#     claim can expire.
#
# Usage: nohup scripts/tpu_bench_watcher.sh [outdir] &
set -u
cd "$(dirname "$0")/.."           # bench.py lives at the repo root
PYTHON=${PYTHON:-python}
command -v "$PYTHON" > /dev/null || PYTHON=python3
OUT=${1:-/tmp/tpu_bench}
mkdir -p "$OUT"
COOLDOWN=${T2OMCA_WATCHER_COOLDOWN:-600}
N=0
while :; do
  N=$((N + 1))
  LOG="$OUT/attempt_$N.log"
  echo "[watcher] attempt $N at $(date -u +%FT%TZ)" >> "$OUT/watcher.log"
  "$PYTHON" bench.py --all > "$LOG" 2>&1
  RC=$?
  # full success only: rc==0 (bench_all ran every leg; per-leg failures
  # are caught internally and noted on stderr) AND a real numeric value
  # landed. A crash after a partial emit (rc!=0) must keep retrying.
  if [ "$RC" -eq 0 ] && grep -q '"value": *[0-9]' "$LOG"; then
    echo "[watcher] SUCCESS on attempt $N (rc=$RC)" >> "$OUT/watcher.log"
    cp "$LOG" "$OUT/SUCCESS.log"
    break
  fi
  # a deterministic post-headline hard crash (rc!=0, but the headline
  # value landed — bench_all emits most-important-first for exactly this
  # case) must not loop forever: accept the partial set after 3 tries
  if [ "$N" -ge 3 ] && grep -q '"value": *[0-9]' "$LOG"; then
    echo "[watcher] PARTIAL accepted on attempt $N (rc=$RC)" \
      >> "$OUT/watcher.log"
    cp "$LOG" "$OUT/PARTIAL.log"
    break
  fi
  echo "[watcher] attempt $N failed (rc=$RC); cooling down ${COOLDOWN}s" \
    >> "$OUT/watcher.log"
  sleep "$COOLDOWN"
done
